"""Commit-path span tracing: record shape, cross-role stitching, stream
well-formedness, and determinism under the simulator.

Reference: flow/Trace.h g_traceBatch attach/event records
(NativeAPI.actor.cpp debugTransaction, MasterProxyServer.actor.cpp
commitBatch probes) extended here into Begin/End span pairs; the analyzer
lives in tools/trace_analyze.py.
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.tools import trace_analyze as TA
from foundationdb_tpu.utils import trace as T


@pytest.fixture(autouse=True)
def _clean_trace():
    T.g_trace_batch._events.clear()
    yield
    T.set_sink(None)
    T.disable_suppression()
    T.g_trace_batch._events.clear()


# ------------------------------------------------------------- primitives

def test_span_record_shape_and_explicit_time():
    tb = T.TraceBatch()
    tb.span_begin("CommitSpan", "b0.1", "Proxy.Resolve", at=12.5)
    tb.span_end("CommitSpan", "b0.1", "Proxy.Resolve", at=12.75)
    begin, end = tb._events
    assert begin == {"Type": "CommitSpan", "Time": 12.5, "ID": "b0.1",
                     "Span": "Proxy.Resolve", "Phase": "Begin"}
    assert end["Phase"] == "End" and end["Time"] == 12.75


def test_span_buffer_auto_dumps_at_capacity():
    got: list[dict] = []
    T.set_sink(got.append)
    tb = T.TraceBatch(max_buffer=4)
    for i in range(4):
        tb.span_begin("CommitSpan", f"x{i}", "Stage")
    assert tb._events == [] and len(got) == 4


# --------------------------------------------------------- trace_analyze

def _mk(ident, span, t0, t1):
    return [{"Type": "CommitSpan", "Time": t0, "ID": ident, "Span": span,
             "Phase": "Begin"},
            {"Type": "CommitSpan", "Time": t1, "ID": ident, "Span": span,
             "Phase": "End"}]


def test_analyze_pairs_stitches_and_ranks():
    events = (_mk("c1", "Client.Commit", 0.0, 0.05)
              + [{"Type": "CommitAttach", "Time": 0.01, "ID": "c1",
                  "To": "b0.7"}]
              + _mk("b0.7", "Proxy.Resolve", 0.01, 0.02)
              + [{"Type": "CommitAttach", "Time": 0.02, "ID": "b0.7",
                  "To": "v900"}]
              + _mk("v900", "TLog.Commit", 0.02, 0.04)
              + _mk("c2", "Client.Commit", 0.0, 0.01))
    rep = TA.analyze(events)
    assert rep["spans"] == 4 and rep["unmatched"] == 0
    # c1/b0.7/v900 collapse into one flow; c2 stands alone
    assert rep["flows"] == 2
    flows = TA.transaction_timelines(events)
    big = max(flows.values(), key=len)
    assert [s["Span"] for s in big] == ["Client.Commit", "Proxy.Resolve",
                                       "TLog.Commit"]
    st = rep["stages"]["Client.Commit"]
    assert st["n"] == 2 and st["p50"] == 0.01 and st["p99"] == 0.05


def test_analyze_fifo_pairing_for_concurrent_same_stage_spans():
    # two overlapping spans on ONE (id, stage) pair match in emission order
    events = [
        {"Type": "CommitSpan", "Time": 0.0, "ID": "v1",
         "Span": "Resolver.ReadbackWait", "Phase": "Begin"},
        {"Type": "CommitSpan", "Time": 0.1, "ID": "v1",
         "Span": "Resolver.ReadbackWait", "Phase": "Begin"},
        {"Type": "CommitSpan", "Time": 0.2, "ID": "v1",
         "Span": "Resolver.ReadbackWait", "Phase": "End"},
        {"Type": "CommitSpan", "Time": 0.4, "ID": "v1",
         "Span": "Resolver.ReadbackWait", "Phase": "End"},
    ]
    spans, unmatched = TA.pair_spans(events)
    assert not unmatched
    assert sorted(round(s["Duration"], 6) for s in spans) == [0.2, 0.3]


def test_check_well_formed_catches_violations():
    good = _mk("a", "S", 0.0, 1.0)
    assert TA.check_well_formed(good) == []
    assert TA.check_well_formed(good[:1])  # dangling Begin
    assert TA.check_well_formed(good[1:])  # End without Begin
    backwards = _mk("b", "S", 5.0, 1.0)
    assert any("ends before" in p for p in TA.check_well_formed(backwards))
    dangling = good + [{"Type": "CommitAttach", "Time": 0.0, "ID": "ghost1",
                        "To": "ghost2"}]
    assert any("dangling attach" in p for p in TA.check_well_formed(dangling))


def test_check_well_formed_queue_delay_vs_version_fetch():
    # the queue-delay span covers arrival -> batch dispatch, so it must end
    # by the time the same batch's commit-version fetch begins
    ok = (_mk("b0.1", "Proxy.QueueDelay", 0.0, 1.0)
          + _mk("b0.1", "Proxy.GetCommitVersion", 1.0, 1.5))
    assert TA.check_well_formed(ok) == []
    bad = (_mk("b0.2", "Proxy.QueueDelay", 0.0, 1.2)
           + _mk("b0.2", "Proxy.GetCommitVersion", 1.0, 1.5))
    assert any("queue delay overlaps" in p
               for p in TA.check_well_formed(bad))


def test_queueing_ratio_rollup():
    events = (_mk("c1", "Client.Commit", 0.0, 0.09)
              + _mk("b0.1", "Proxy.GetCommitVersion", 0.0, 0.01)
              + _mk("b0.1", "Proxy.Resolve", 0.01, 0.02)
              + _mk("b0.1", "Proxy.TLogPush", 0.02, 0.04))
    rep = TA.analyze(events)
    # 0.09 client / (0.01 + 0.01 + 0.02) server
    assert rep["queueing_ratio"] == pytest.approx(2.25)
    # Proxy.QueueDelay must NOT enter the denominator: it IS the queueing
    rep2 = TA.analyze(events + _mk("b0.1", "Proxy.QueueDelay", 0.0, 5.0))
    assert rep2["queueing_ratio"] == pytest.approx(2.25)
    # no client spans -> no ratio
    assert TA.analyze(events[2:])["queueing_ratio"] is None


def test_load_events_skips_torn_lines(tmp_path):
    p = tmp_path / "trace.jsonl"
    p.write_text('{"Type": "CommitSpan", "ID": "x"}\n'
                 "\n"
                 '{"Type": "Commit')  # torn tail from a killed process
    events = TA.load_events([str(p)])
    assert len(events) == 1 and events[0]["ID"] == "x"


# ------------------------------------------------- simulated commit path

EXPECTED_STAGES = {
    "Client.GRV", "Client.Commit", "Proxy.BatchAssembly",
    "Proxy.QueueDelay", "Proxy.GetCommitVersion", "Proxy.Resolve",
    "Proxy.TLogPush", "Proxy.Reply", "Resolver.Dispatch", "TLog.Commit",
}


def _run_workload(seed: int) -> list[dict]:
    """A small commit workload on a fresh SimCluster with a capture sink;
    returns every record that reached the sink."""
    from foundationdb_tpu.server.cluster import SimCluster
    from foundationdb_tpu.utils.knobs import KNOBS

    got: list[dict] = []
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    try:
        T.set_sink(got.append)
        T.enable_suppression()  # prod-shaped config: spans must fit under it
        c = SimCluster(seed=seed, n_proxies=2, n_resolvers=1, n_tlogs=1,
                       n_storage=1)
        db = c.database()

        async def client(cid: int):
            for i in range(6):
                tr = db.create_transaction()
                await tr.get(b"s%d.%d" % (cid, i))
                tr.set(b"s%d.%d" % (cid, i), b"v")
                await tr.commit()
        c.run_all([client(i) for i in range(3)], max_time=600.0)
        T.g_trace_batch.dump()
        T.flush_suppressed()
    finally:
        T.set_sink(None)
        T.disable_suppression()
        KNOBS.reset()
    return got


def test_sim_span_stream_well_formed_and_stitched():
    got = _run_workload(seed=11)
    # every stage of the pipeline shows up
    seen_stages = {e["Span"] for e in got if "Span" in e}
    assert EXPECTED_STAGES <= seen_stages, seen_stages
    # stream invariants: every Begin has an End, no attach is dead weight
    assert TA.check_well_formed(got) == []
    # cross-role stitching: each client commit id reaches a version ident
    # (client -> proxy batch -> commit version) through the attach records
    uf = TA.stitch(got)
    commit_ids = {e["ID"] for e in got
                  if e.get("Span") == "Client.Commit" and e["ID"].startswith("c")}
    version_ids = {e["ID"] for e in got
                   if e.get("Span") == "TLog.Commit"}
    assert commit_ids and version_ids
    version_roots = {uf.find(v) for v in version_ids}
    stitched = [cid for cid in commit_ids if uf.find(cid) in version_roots]
    assert stitched, "no client commit id stitched through to a version"
    # spans rode under the suppression threshold: nothing was dropped
    assert not [e for e in got if e["Type"] == "TraceEventsSuppressed"]
    # analyzer end-to-end over the sim stream
    rep = TA.analyze(got)
    assert rep["unmatched"] == 0
    for stage in EXPECTED_STAGES:
        assert rep["stages"][stage]["n"] >= 1


def test_sim_span_stream_deterministic():
    """Same seed => same span/attach/probe sequence modulo wall-clock
    fields (span Times are virtual and must match exactly too; counter
    TraceEvents carry wall time and are excluded)."""
    def batch_records(events):
        return [{k: v for k, v in e.items()}
                for e in events
                if e.get("Type") in ("CommitSpan", "CommitAttach",
                                     "CommitDebug")]
    a = batch_records(_run_workload(seed=23))
    b = batch_records(_run_workload(seed=23))
    assert a == b
    c = batch_records(_run_workload(seed=24))
    assert [e.get("Span") for e in a] != [e.get("Span") for e in c] or a != c


# ------------------------------------------------------ cluster-wide status

def test_status_carries_all_six_role_counters():
    """The CC's status JSON aggregates a counter snapshot from every role
    kind (master, proxy, resolver, log, storage, ratekeeper) plus the
    cluster-wide workload rollup."""
    from foundationdb_tpu.server.cluster import RecoverableCluster
    from foundationdb_tpu.utils.knobs import KNOBS

    KNOBS.set("CONFLICT_BACKEND", "oracle")
    try:
        c = RecoverableCluster(seed=5)
        db = c.database()

        async def work():
            await db.refresh(max_wait=300.0)
            for i in range(8):
                async def fn(tr, i=i):
                    await tr.get(b"st%d" % i)
                    tr.set(b"st%d" % i, b"v")
                await db.transact(fn, max_retries=50)
            return await db.get_status()
        status = c.run(c.loop.spawn(work()), max_time=60_000.0)
    finally:
        KNOBS.reset()

    roles = status["cluster"]["roles"]
    by_kind: dict[str, list[dict]] = {}
    for entry in roles:
        by_kind.setdefault(entry["role"], []).append(entry)
    for kind in ("master", "proxy", "resolver", "log", "storage",
                 "ratekeeper", "cluster_controller"):
        assert kind in by_kind, f"missing {kind}: {sorted(by_kind)}"
        assert any("counters" in e for e in by_kind[kind]), kind
    # the snapshots reflect the traffic that just ran
    master = next(e["counters"] for e in by_kind["master"] if "counters" in e)
    assert master["VersionRequests"] >= 8
    resolver = next(e["counters"] for e in by_kind["resolver"]
                    if "counters" in e)
    assert resolver["TxnResolved"] >= 8
    assert resolver["Backend"] == "oracle"
    log = next(e["counters"] for e in by_kind["log"] if "counters" in e)
    assert log["Commits"] >= 8 and log["BytesIn"] > 0
    storage_total = sum(e["counters"]["MutationsApplied"]
                       for e in by_kind["storage"] if "counters" in e)
    assert storage_total >= 8
    rk = next(e["counters"] for e in by_kind["ratekeeper"] if "counters" in e)
    assert rk["TPS"] > 0
    workload = status["cluster"]["workload"]
    assert workload["transactions_committed"] >= 8
    assert workload["mutation_bytes"] > 0
    assert workload["commit_batches"] >= 1
