"""The measured CPU baseline (native/skiplist_baseline.c) must keep
building and producing sane numbers — bench.py divides by it."""

import json
import os
import subprocess

import pytest

SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "foundationdb_tpu", "native", "skiplist_baseline.c")


def test_skiplist_baseline_builds_and_runs(tmp_path):
    exe = str(tmp_path / "skb")
    try:
        proc = subprocess.run(["cc", "-O2", "-o", exe, SRC],
                              capture_output=True, text=True, timeout=120)
    except FileNotFoundError:
        pytest.skip("no C toolchain: cc not on PATH")
    if proc.returncode != 0:
        pytest.skip(f"no C toolchain: {proc.stderr[-200:]}")
    out = subprocess.run([exe, "500", "30"], capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout.strip())
    assert rep["txns_per_batch"] == 500 and rep["batches"] == 30
    assert rep["txns_per_sec"] > 1000
    # skipListTest's workload statistics: ~5% of txns conflict (sparse
    # ranges over a 20M keyspace, 125k-txn history window)
    assert 0.85 <= rep["committed_frac"] <= 0.999, rep
