"""The measured CPU baseline (native/skiplist_baseline.c) must keep
building and producing sane numbers — bench.py divides by it."""

import json
import os
import subprocess

import pytest

SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "foundationdb_tpu", "native", "skiplist_baseline.c")


def test_skiplist_baseline_builds_and_runs(tmp_path):
    exe = str(tmp_path / "skb")
    try:
        proc = subprocess.run(["cc", "-O2", "-o", exe, SRC],
                              capture_output=True, text=True, timeout=120)
    except FileNotFoundError:
        pytest.skip("no C toolchain: cc not on PATH")
    if proc.returncode != 0:
        pytest.skip(f"no C toolchain: {proc.stderr[-200:]}")
    out = subprocess.run([exe, "500", "30"], capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout.strip())
    assert rep["txns_per_batch"] == 500 and rep["batches"] == 30
    assert rep["txns_per_sec"] > 1000
    # skipListTest's workload statistics: ~5% of txns conflict (sparse
    # ranges over a 20M keyspace, 125k-txn history window)
    assert 0.85 <= rep["committed_frac"] <= 0.999, rep


def test_skiplist_baseline_decision_parity_with_oracle(tmp_path):
    """The measured baseline must make the SAME abort decisions as the
    independent Python oracle on identical batches (VERDICT r4 weak 5): a
    subtly wrong baseline would silently skew vs_baseline. Mirrors the
    reference's own cross-check of its fast path against a naive oracle
    (SkipList.cpp:1394 miniConflictSetTest)."""
    import random
    import struct

    from foundationdb_tpu.ops.batch import TxnConflictInfo
    from foundationdb_tpu.ops.conflict_oracle import OracleConflictSet
    from foundationdb_tpu.utils.knobs import KNOBS

    exe = str(tmp_path / "skb")
    try:
        proc = subprocess.run(["cc", "-O2", "-o", exe, SRC],
                              capture_output=True, text=True, timeout=120)
    except FileNotFoundError:
        pytest.skip("no C toolchain: cc not on PATH")
    if proc.returncode != 0:
        pytest.skip(f"no C toolchain: {proc.stderr[-200:]}")

    B, T = 40, 200
    KEYSPACE = 5_000  # dense: plenty of real conflicts
    WB = 8  # window in batches
    rng = random.Random(20260730)
    batches = []
    lines = [f"{B} {T}"]
    for i in range(B):
        snapshot, now, floor = i, i + WB, i
        lines.append(f"{snapshot} {now} {floor}")
        rows = []
        for _ in range(T):
            k1, s1 = rng.randrange(KEYSPACE), 1 + rng.randrange(10)
            k2, s2 = rng.randrange(KEYSPACE), 1 + rng.randrange(10)
            rows.append((k1, s1, k2, s2))
            lines.append(f"{k1} {s1} {k2} {s2}")
        batches.append((snapshot, now, rows))
    out = subprocess.run([exe, "--parity"], input="\n".join(lines) + "\n",
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    c_status = out.stdout.split()
    assert len(c_status) == B

    def setk(v):  # the baseline's 16-byte setK key layout
        return b"." * 12 + struct.pack(">I", v)

    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", WB)
    oracle = OracleConflictSet()
    mismatches = []
    conflicts = 0
    for bi, (snapshot, now, rows) in enumerate(batches):
        txns = [TxnConflictInfo(
            read_snapshot=snapshot,
            read_ranges=[(setk(k1), setk(k1 + s1))],
            write_ranges=[(setk(k2), setk(k2 + s2))])
            for k1, s1, k2, s2 in rows]
        want = oracle.detect(txns, now)
        got = [int(ch) for ch in c_status[bi]]
        conflicts += sum(1 for s in got if s == 0)
        for j, (w, g) in enumerate(zip(want, got)):
            if w != g:
                mismatches.append((bi, j, w, g, rows[j]))
    assert not mismatches, \
        f"{len(mismatches)} decision mismatches, first 5: {mismatches[:5]}"
    assert conflicts > 50, f"workload produced too few conflicts ({conflicts})"
