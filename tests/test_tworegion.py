"""Two-region replication: satellite log sets, log routers, region failover.

Reference: fdbserver/TagPartitionedLogSystem.actor.cpp (satellite log sets in
the push quorum :398-417), fdbserver/LogRouter.actor.cpp (remote region pulls
each tag once across the WAN), documentation "Configuring regions"
(configuration.rst): commits replicate synchronously to a satellite outside
the primary dc and asynchronously to a standby region; losing the whole
primary region fails over with zero acked-commit loss.
"""

import pytest

from foundationdb_tpu.server.cluster import RecoverableCluster
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


N = 5


def key(i):
    return b"cycle/%02d" % i


async def setup_ring(tr):
    for i in range(N):
        tr.set(key(i), b"%02d" % ((i + 1) % N))


def make_rotate(c):
    async def rotate(tr):
        r = c.rng.randint(0, N - 1)
        a = key(r)
        b_idx = int(await tr.get(a))
        b = key(b_idx)
        c_idx = int(await tr.get(b))
        ck = key(c_idx)
        d_idx = int(await tr.get(ck))
        tr.set(a, b"%02d" % c_idx)
        tr.set(b, b"%02d" % d_idx)
        tr.set(ck, b"%02d" % b_idx)
    return rotate


async def check_ring(db):
    async def read_ring(tr):
        seen = set()
        i = 0
        for _ in range(N):
            seen.add(i)
            i = int(await tr.get(key(i)))
        return i, seen
    i, seen = await db.transact(read_ring, max_retries=500)
    assert i == 0 and len(seen) == N, f"ring broken: {seen}"


def client(c):
    proc = c.net.new_process("client:0", dc_id="client")
    from foundationdb_tpu.client.database import Database
    return Database(proc, coordinators=c.coordinators, rng=c.rng.fork())


def test_satellite_log_set_in_commit_quorum():
    """The recruited generation carries a satellite member outside the
    primary dc, split-recorded via LogEpoch.n_primary, and the pipeline
    serves transactions through the two-set push quorum."""
    c = RecoverableCluster.two_region(seed=41)
    db = client(c)

    async def t():
        await db.refresh()
        await db.transact(setup_ring)
        await check_ring(db)
        ep = c.current_cc().dbinfo.log_epochs[-1]
        assert ep.n_primary == 1 and len(ep.addrs) == 2, ep
        prim, sat = ep.addrs[0], ep.addrs[1]
        assert c.net.processes[prim].dc_id == "dc0"
        assert c.net.processes[sat].dc_id == "sat0"
        # the satellite holds the mutation log (it is in the commit quorum):
        # its TLog generation has data for the storage tags
        host = c.net.processes[sat].worker.roles["tloghost"]
        t_sat = host.generations[ep.uids[1]]
        assert t_sat.version.get() > 0
        assert any(t_sat.messages.values()) or t_sat.popped

    c.run(c.loop.spawn(t()), max_time=60_000.0)


def test_remote_region_replicates_async_via_log_routers():
    """Standby-region storages receive every mutation THROUGH their log
    router (their epoch view points at the router, not at the primary
    TLogs) and converge to the primary's data."""
    c = RecoverableCluster.two_region(seed=42)
    db = client(c)

    async def t():
        await db.refresh()
        await db.transact(setup_ring)
        # locate the dc1 storage role and its router-routed epoch view
        remote = [p for p in c.storage_worker_procs if p.dc_id == "dc1"]
        assert remote
        ss = None
        for _ in range(100):
            for p in remote:
                for k, role in getattr(p.worker, "roles", {}).items():
                    if k.startswith("storage:"):
                        ss = role
            if ss is not None:
                break
            await c.loop.delay(0.2)
        assert ss is not None, "no remote storage recruited"
        ep = ss.log_epochs[-1]
        assert len(ep.addrs) == 1 and c.net.processes[ep.addrs[0]].dc_id == "dc1", \
            f"remote storage must pull via its region's log router: {ep}"
        assert ep.uids and "lr" in ep.uids[0]
        # async convergence: the ring appears on the remote replica
        for _ in range(200):
            v = ss.version.get()
            if v > 0 and all(ss.data.get(key(i), v) is not None
                             for i in range(N)):
                break
            await c.loop.delay(0.2)
        v = ss.version.get()
        ring = {i: int(ss.data.get(key(i), v)) for i in range(N)}
        assert set(ring.values()) == set(range(N)), ring

    c.run(c.loop.spawn(t()), max_time=60_000.0)


def test_region_failover_loses_no_acked_commit():
    """THE two-region guarantee (VERDICT r4 ask 3): commits replicate to
    the standby region, the whole primary region dies, and the cluster
    recovers in region B with every acknowledged commit intact (the
    satellite log fences + supplies the tail)."""
    c = RecoverableCluster.two_region(seed=43)
    db = client(c)
    rotations = 8

    async def t():
        await db.refresh()
        await db.transact(setup_ring)
        rotate = make_rotate(c)
        for i in range(rotations):
            async def w(tr, i=i):
                await rotate(tr)
                tr.set(b"acked", b"%04d" % (i + 1))
            await db.transact(w, max_retries=500)
        # quiesced: everything below is acknowledged. Lose region A.
        c.kill_dc("dc0")
        # the cluster must recover in dc1 with zero acked loss
        async def read_acked(tr):
            return await tr.get(b"acked")
        acked = await db.transact(read_acked, max_retries=2000)
        assert acked == b"%04d" % rotations, \
            f"acked commit lost across region failover: {acked!r}"
        await check_ring(db)
        cc = c.current_cc()
        assert cc is not None
        master = cc.dbinfo.master
        assert c.net.processes[master].dc_id == "dc1", \
            f"recovery must have failed over to dc1, master={master}"
        # new generation's primary logs live in dc1 too
        ep = cc.dbinfo.log_epochs[-1]
        np_ = ep.n_primary or len(ep.addrs)
        assert all(c.net.processes[a].dc_id == "dc1"
                   for a in ep.addrs[:np_]), ep

    c.run(c.loop.spawn(t()), max_time=120_000.0)


def test_region_failover_with_device_backend():
    """Composition of the round's features: the DEVICE conflict engine
    serving commits while a whole-region failover happens — recoveries
    re-instantiate the engine (fresh conflict state) in the surviving
    region with zero acked loss."""
    KNOBS.set("CONFLICT_BACKEND", "device")
    KNOBS.set("CONFLICT_CPU_FALLBACK", "jax")  # exercise the JAX serving path in CI
    KNOBS.set("CONFLICT_BATCH_TXNS", 16)
    KNOBS.set("CONFLICT_BATCH_READS_PER_TXN", 2)
    KNOBS.set("CONFLICT_BATCH_WRITES_PER_TXN", 2)
    KNOBS.set("CONFLICT_STATE_CAPACITY", 2048)
    try:
        c = RecoverableCluster.two_region(seed=47)
        db = client(c)

        async def t():
            await db.refresh()
            await db.transact(setup_ring)
            rotate = make_rotate(c)
            for i in range(4):
                async def w(tr, i=i):
                    await rotate(tr)
                    tr.set(b"acked", b"%04d" % (i + 1))
                await db.transact(w, max_retries=500)
            c.kill_dc("dc0")

            async def read_acked(tr):
                return await tr.get(b"acked")
            acked = await db.transact(read_acked, max_retries=2000)
            assert acked == b"0004", acked
            await check_ring(db)
            cc = c.current_cc()
            assert c.net.processes[cc.dbinfo.master].dc_id == "dc1"

        c.run(c.loop.spawn(t()), max_time=120_000.0)
    finally:
        KNOBS.reset()


@pytest.mark.slow
@pytest.mark.xfail(
    strict=True,
    raises=__import__("foundationdb_tpu.testing.simulated_cluster",
                      fromlist=["SpecFailure"]).SpecFailure,
    reason="ROADMAP 'two-region durability under attrition': an acked "
           "commit rolls back across a region recovery — the per-key "
           "commit ledger loses proven increments. The recovery-version "
           "selection across satellite + log-router feeds is the suspect "
           "(TagPartitionedLogSystem.actor.cpp epoch-end machinery). When "
           "this XPASSes, the bug is fixed: delete this test, un-pin the "
           "zipfian spec from flat clusters (needs='flat'), and promote a "
           "region-failover ledger spec into tier-1.")
def test_two_region_acked_rollback_repro():
    """The still-open acked-rollback bug, pinned as a strict xfail so the
    suite (not a prose repro line in ROADMAP) tracks it. Equivalent CLI:

        python -m foundationdb_tpu.testing.simulated_cluster \
            --seed 3 --spec zipfian-hotkey --duration 50

    with the spec's needs="flat" guard removed — seed 3 draws a two_region
    cluster, which the zipfian spec normally refuses precisely because of
    this bug. The ledger check fails with acked increments missing after
    an attrition-driven recovery (~182 commits in, two increments gone).
    """
    import dataclasses

    from foundationdb_tpu.testing import simulated_cluster as SC

    spec = dataclasses.replace(SC.SPECS["zipfian-hotkey"], needs="")
    result = SC.run_randomized_spec(3, spec=spec, duration=50.0)
    # unreachable until the bug is fixed (xfail strict trips on pass)
    assert result.draw.replication == "two_region"
