"""C-ABI surface + binding conformance tester against a real cluster.

Reference: bindings/c/fdb_c.h (the stable ABI: network thread, futures,
error codes), bindings/bindingtester/bindingtester.py (the stack-machine
conformance harness). The tester runs one seeded instruction stream through
the C-ABI-shaped client AND the native async client on separate prefixes of
one real-transport cluster, then diffs the result stacks and final data.
"""

import threading

import pytest

import bench_e2e
from foundationdb_tpu.bindings import bindingtester, fdb_c


@pytest.fixture
def real_cluster(tmp_path):
    procs, _labels, p_proxies, boundaries, teams, _grv = \
        bench_e2e._boot_cluster(str(tmp_path), "oracle", n_proxies=0,
                                n_storage=1)
    yield p_proxies, boundaries, teams
    for p in procs:
        p.terminate()
    for p in procs:
        p.wait(timeout=10)


def test_capi_surface_and_bindingtester(real_cluster):
    p_proxies, boundaries, teams = real_cluster
    fdb_c._reset_for_tests()
    # the fdb_c.h lifecycle contract
    assert fdb_c.fdb_setup_network() != 0, "setup before version must fail"
    assert fdb_c.fdb_select_api_version(fdb_c.HEADER_API_VERSION + 1) != 0
    assert fdb_c.fdb_select_api_version(610) == 0
    assert fdb_c.fdb_select_api_version(610) == 0  # idempotent re-select
    assert fdb_c.fdb_setup_network() == 0
    assert fdb_c.fdb_setup_network() != 0, "double setup must fail"
    net_thread = threading.Thread(target=fdb_c.fdb_run_network, daemon=True)
    net_thread.start()
    try:
        cluster = {"proxies": p_proxies,
                   "boundaries": boundaries,
                   "storages": [list(t) for t in teams]}
        err, db = fdb_c.fdb_create_database(cluster)
        assert err == 0 and db is not None

        # basic future semantics: get on an empty key, callback delivery
        tr = db.create_transaction()
        fut = tr.get(b"bt_c/none")
        assert fut.block_until_ready() == 0 and fut.is_ready()
        err, present, v = fut.get_value()
        assert (err, present, v) == (0, False, None)
        fired = threading.Event()
        fut2 = tr.get_read_version()
        fut2.set_callback(lambda f, arg: fired.set(), None)
        fut2.block_until_ready()
        assert fired.wait(5.0)
        # error mapping: a conflict surfaces as the not_committed CODE
        assert fdb_c.fdb_get_error(1020) == "not_committed"
        assert fdb_c.fdb_error_predicate("RETRYABLE", 1020)
        assert fdb_c.fdb_error_predicate("MAYBE_COMMITTED", 1021)
        assert not fdb_c.fdb_error_predicate("RETRYABLE", 4100)

        # the conformance run: identical seeded streams through the C-ABI
        # machine and the native client, stacks + final data must match
        from foundationdb_tpu.client.database import Database, LocationCache
        from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
        import socket
        loop = RealEventLoop()
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        addr = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        client = NetTransport(loop, addr)
        client.start()
        ndb = Database(client.process, proxies=list(p_proxies),
                       locations=LocationCache(
                           list(boundaries), [list(t) for t in teams]))
        checked = bindingtester.compare_runs(977, 2000, db, loop, ndb)
        checked += bindingtester.compare_runs(31337, 1000, db, loop, ndb,
                                              prefix_c=b"bt2_c/",
                                              prefix_n=b"bt2_n/")
        assert checked > 500
        client.close()
    finally:
        fdb_c.fdb_stop_network()
        net_thread.join(timeout=10)
        fdb_c._reset_for_tests()


def test_multiversion_client_selection():
    """MultiVersionApi selection rules (MultiVersionTransaction.actor.cpp):
    registration gates, most-compatible-library election, unsupported
    versions rejected, disable option pinning the local client."""
    import types

    from foundationdb_tpu.bindings import fdb_c
    from foundationdb_tpu.bindings.multiversion import MultiVersionApi

    def fake_client(max_api):
        m = types.SimpleNamespace()
        m.fdb_get_max_api_version = lambda: max_api
        m.fdb_select_api_version = lambda v: 0 if v <= max_api else 1
        m.fdb_create_database = lambda cluster: (0, ("db", max_api))
        return m

    api = MultiVersionApi()
    assert api.add_external_client("v700", fake_client(700)) == 0
    assert api.add_external_client("v520", fake_client(520)) == 0
    assert api.add_external_client("bogus", object()) != 0  # no surface
    # version above every library -> rejected
    assert api.fdb_select_api_version(800) != 0
    # 600 fits v700 and the local 610 library but NOT v520: the election
    # picks the most compatible (smallest max >= 600) = local 610
    fdb_c._reset_for_tests()
    assert api.fdb_select_api_version(600) == 0
    assert api.active_client is fdb_c
    # re-select with a different version fails; same version is idempotent
    assert api.fdb_select_api_version(520) != 0
    assert api.fdb_select_api_version(600) == 0
    # surface delegation reaches the active client
    assert api.fdb_get_max_api_version() == fdb_c.HEADER_API_VERSION

    # a 500-level request elects the v520 library over local/700
    api2 = MultiVersionApi()
    api2.add_external_client("v700", fake_client(700))
    api2.add_external_client("v520", fake_client(520))
    fdb_c._reset_for_tests()
    assert api2.fdb_select_api_version(500) == 0
    assert api2.fdb_get_max_api_version() == 520
    err, db = api2.fdb_create_database({})
    assert (err, db) == (0, ("db", 520))

    # disable option pins the local client regardless of externals
    api3 = MultiVersionApi()
    api3.add_external_client("v520", fake_client(520))
    assert api3.disable_multi_version_client_api() == 0
    fdb_c._reset_for_tests()
    assert api3.fdb_select_api_version(500) == 0
    assert api3.active_client is fdb_c
    fdb_c._reset_for_tests()
