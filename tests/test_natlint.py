"""natlint (NAT001..NAT007): fixtures both ways per rule, the enforcement
gate over the real package, and mutation proofs against fdb_native.c.

The mutation tests are the teeth: each takes the REAL extension source,
re-introduces one historical violation shape (deletes a Py_DECREF from an
error ladder, drops the GIL window, removes the decoded-count guard...) and
asserts the rule catches it — while the unmutated file stays clean. A rule
that passes its toy fixtures but goes blind on 2000 lines of real C fails
here.
"""

from __future__ import annotations

import os
import textwrap

from foundationdb_tpu.analysis import flowlint
from foundationdb_tpu.analysis.__main__ import main as lint_main
from foundationdb_tpu.analysis.natlint import analyze_c_source

_C_SRC = os.path.join(os.path.dirname(__file__), "..", "foundationdb_tpu",
                      "native", "fdb_native.c")


def _details(src: str, rule: str | None = None) -> list[str]:
    return [f.detail for f in analyze_c_source(textwrap.dedent(src))
            if rule is None or f.rule == rule]


def _real_source() -> str:
    with open(_C_SRC, encoding="utf-8") as f:
        return f.read()


# ---------------------------------------------------------------------------
# family registration
# ---------------------------------------------------------------------------

def test_family_registered():
    assert "nat" in flowlint.FAMILIES
    assert flowlint.rule_family("NAT001") == "nat"
    codes = sorted(r.code for r in flowlint.active_rules("nat"))
    assert codes == [f"NAT00{i}" for i in range(1, 8)]
    # and the CLI accepts the family
    assert lint_main(["--family", "nat", "--list-rules"]) == 0


# ---------------------------------------------------------------------------
# NAT001 — unchecked allocation
# ---------------------------------------------------------------------------

def test_nat001_flags_use_before_null_test():
    src = """
    static PyObject *f(PyObject *o) {
        char *p = malloc(16);
        p[0] = 1;
        return NULL;
    }
    """
    assert "unchecked-alloc:p" in _details(src, "NAT001")


def test_nat001_accepts_null_test_and_ternary():
    src = """
    static PyObject *f(PyObject *o) {
        char *p = malloc(16);
        if (!p)
            return NULL;
        p[0] = 1;
        PyObject *v = PyBytes_FromStringAndSize(p, 16);
        PyObject *pair = v ? PyTuple_Pack(1, v) : NULL;
        return pair;
    }
    """
    assert _details(src, "NAT001") == []


def test_nat001_flags_inline_discarded_allocation():
    src = """
    static int f(PyObject *o) {
        use(malloc(8));
        return 0;
    }
    """
    assert "discarded-alloc:malloc" in _details(src, "NAT001")


# ---------------------------------------------------------------------------
# NAT002 — refcount balance on error paths
# ---------------------------------------------------------------------------

def test_nat002_flags_early_return_leaking_owned_ref():
    src = """
    static PyObject *f(PyObject *o) {
        PyObject *a = PyList_New(0);
        if (!a)
            return NULL;
        PyObject *b = PyDict_New();
        if (!b)
            return NULL;
        Py_DECREF(b);
        return a;
    }
    """
    assert "leak:a@return" in _details(src, "NAT002")


def test_nat002_accepts_goto_ladder_that_releases_everything():
    src = """
    static PyObject *f(PyObject *o) {
        PyObject *a = PyList_New(0);
        if (!a)
            return NULL;
        PyObject *b = PyDict_New();
        if (!b)
            goto err;
        Py_DECREF(b);
        return a;
    err:
        Py_XDECREF(a);
        return NULL;
    }
    """
    assert _details(src, "NAT002") == []


def test_nat002_ladder_missing_one_release_is_flagged():
    src = """
    static PyObject *f(PyObject *o) {
        PyObject *a = PyList_New(0);
        if (!a)
            return NULL;
        PyObject *b = PyDict_New();
        if (!b)
            goto err;
        Py_DECREF(b);
        return a;
    err:
        return NULL;
    }
    """
    assert "leak:a@err" in _details(src, "NAT002")


def test_nat002_stolen_and_aliased_refs_end_ownership():
    src = """
    static PyObject *f(PyObject *o) {
        PyObject *out = PyList_New(1);
        if (!out)
            return NULL;
        PyObject *v = PyLong_FromLong(1);
        if (!v)
            goto err;
        PyList_SET_ITEM(out, 0, v);
        return out;
    err:
        Py_DECREF(out);
        return NULL;
    }
    """
    assert _details(src, "NAT002") == []


# ---------------------------------------------------------------------------
# NAT003 — unchecked fallible calls
# ---------------------------------------------------------------------------

def test_nat003_flags_ignored_error_return():
    src = """
    static int f(PyObject *lst, PyObject *item) {
        PyList_Append(lst, item);
        return 0;
    }
    """
    assert "ignored-call:PyList_Append" in _details(src, "NAT003")


def test_nat003_errocc_requires_pyerr_occurred():
    bad = """
    static int f(PyObject *o) {
        long v = PyLong_AsLong(o);
        if (v < 0)
            return 0;
        return 1;
    }
    """
    good = """
    static int f(PyObject *o) {
        long v = PyLong_AsLong(o);
        if (v == -1 && PyErr_Occurred())
            return 0;
        return 1;
    }
    """
    assert any(d.startswith("ambiguous-errcheck:PyLong_AsLong")
               for d in _details(bad, "NAT003"))
    assert _details(good, "NAT003") == []


def test_nat003_condition_and_void_cast_accepted():
    src = """
    static int f(PyObject *lst, PyObject *item) {
        if (PyList_Append(lst, item) < 0)
            return -1;
        (void)PyObject_IsTrue(item);
        return 0;
    }
    """
    assert _details(src, "NAT003") == []


# ---------------------------------------------------------------------------
# NAT004 — unbounded buffer access
# ---------------------------------------------------------------------------

def test_nat004_get_item_without_psequence_fast():
    src = """
    static PyObject *f(PyObject *args) {
        PyObject *s = PyTuple_Pack(1, args);
        if (!s)
            return NULL;
        PyObject *x = PySequence_Fast_GET_ITEM(s, 0);
        Py_DECREF(s);
        return x;
    }
    """
    assert "unvalidated-fast:s" in _details(src, "NAT004")


def test_nat004_fast_discipline_with_size_bound_is_clean():
    src = """
    static PyObject *f(PyObject *args) {
        PyObject *s = PySequence_Fast(args, "need seq");
        if (!s)
            return NULL;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(s);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *x = PySequence_Fast_GET_ITEM(s, i);
            use(x);
        }
        Py_DECREF(s);
        return NULL;
    }
    """
    assert _details(src, "NAT004") == []


def test_nat004_buffer_memcpy_needs_len_guard():
    bad = """
    static PyObject *f(PyObject *args) {
        Py_buffer data;
        if (!PyArg_ParseTuple(args, "y*", &data))
            return NULL;
        const uint8_t *b = (const uint8_t *)data.buf;
        uint32_t v;
        memcpy(&v, b, 4);
        PyBuffer_Release(&data);
        return NULL;
    }
    """
    good = bad.replace("uint32_t v;", """uint32_t v;
        if (data.len < 4) {
            PyBuffer_Release(&data);
            return NULL;
        }""")
    assert "unguarded-buffer:b" in _details(bad, "NAT004")
    assert _details(good, "NAT004") == []


# ---------------------------------------------------------------------------
# NAT005 — wire-struct emit parity with schema comments
# ---------------------------------------------------------------------------

_EMIT = """
    static int emit(WBuf *w, uint64_t tid) {
        if (wb_byte(&w, 'R') < 0 || wb_varint(&w, tid) < 0 ||
            wb_varint(&w, %d) < 0)
            return -1;
        return 0;
    }
"""


def test_nat005_schema_count_drift_and_undocumented_emit():
    documented = "/* Foo { a, b, c } */\n" + _EMIT
    assert "schema-count:Foo" in _details(documented % 2, "NAT005")
    assert _details(documented % 3, "NAT005") == []
    assert "undocumented-emit" in _details(_EMIT % 3, "NAT005")


# ---------------------------------------------------------------------------
# NAT006 — GIL across pure-C bulk loops
# ---------------------------------------------------------------------------

_GIL_SRC = """
    static void bulk_xor(uint8_t *p, size_t len) {
        for (size_t i = 0; i < len; i++)
            p[i] ^= 1;
    }
    static PyObject *entry(PyObject *self, PyObject *args) {
        Py_buffer data;
        if (!PyArg_ParseTuple(args, "y*", &data))
            return NULL;
        %s
        PyBuffer_Release(&data);
        Py_RETURN_NONE;
    }
"""


def test_nat006_bulk_loop_without_window_is_flagged():
    bad = _GIL_SRC % "bulk_xor((uint8_t *)data.buf, (size_t)data.len);"
    good = _GIL_SRC % ("Py_BEGIN_ALLOW_THREADS\n"
                       "        bulk_xor((uint8_t *)data.buf, "
                       "(size_t)data.len);\n"
                       "        Py_END_ALLOW_THREADS")
    assert "gil:bulk_xor" in _details(bad, "NAT006")
    assert _details(good, "NAT006") == []


def test_nat006_helper_with_cpython_calls_is_not_bulk():
    src = """
    static void helper(uint8_t *p, size_t len) {
        for (size_t i = 0; i < len; i++)
            PyMem_Free(p);
    }
    static PyObject *entry(PyObject *self, PyObject *args) {
        helper(NULL, 4);
        Py_RETURN_NONE;
    }
    """
    assert _details(src, "NAT006") == []


# ---------------------------------------------------------------------------
# NAT007 — decoded counts trusted before validation
# ---------------------------------------------------------------------------

_DEC_SRC = """
    static PyObject *dec(PyObject *self, PyObject *args) {
        Py_buffer data;
        uint32_t n;
        if (!PyArg_ParseTuple(args, "y*", &data))
            return NULL;
        if (data.len < 4) {
            PyBuffer_Release(&data);
            return NULL;
        }
        memcpy(&n, data.buf, 4);
        %s
        PyObject *out = PyList_New(n);
        PyBuffer_Release(&data);
        return out;
    }
"""


def test_nat007_decoded_count_must_be_validated():
    bad = _DEC_SRC % ""
    good = _DEC_SRC % ("if (n > 1024) {\n"
                       "            PyBuffer_Release(&data);\n"
                       "            return NULL;\n        }")
    assert "decoded:n" in _details(bad, "NAT007")
    assert _details(good, "NAT007") == []


# ---------------------------------------------------------------------------
# inline suppression
# ---------------------------------------------------------------------------

def test_inline_c_suppression_silences_the_named_rule_only():
    src = """
    static PyObject *f(PyObject *o) {
        char *p = malloc(16);
        /* natlint: ignore[NAT001] */
        p[0] = 1;
        PyList_Append(o, o);
        return NULL;
    }
    """
    details = _details(src)
    assert not any(d.startswith("unchecked-alloc") for d in details)
    assert "ignored-call:PyList_Append" in details  # other rules unaffected


# ---------------------------------------------------------------------------
# mutation proofs on the real fdb_native.c
# ---------------------------------------------------------------------------

def _mutate(src: str, old: str, new: str) -> str:
    assert src.count(old) == 1, f"mutation anchor not unique: {old!r}"
    return src.replace(old, new)


def test_mutation_deleting_decref_from_corrupt_ladder_trips_nat002():
    src = _real_source()
    mutated = _mutate(
        src,
        "    corrupt_list:\n"
        "        Py_XDECREF(prev_key);\n"
        "        Py_DECREF(out);\n",
        "    corrupt_list:\n"
        "        Py_XDECREF(prev_key);\n")
    details = [f.detail for f in analyze_c_source(mutated)
               if f.rule == "NAT002"]
    assert "leak:out@corrupt_list" in details
    assert "leak:out@corrupt_list" not in [
        f.detail for f in analyze_c_source(src)]


def test_mutation_deleting_decref_from_early_return_trips_nat002():
    src = _real_source()
    mutated = _mutate(
        src,
        "            if (rc < 0) {\n"
        "                Py_DECREF(it);\n"
        "                return -1;\n"
        "            }",
        "            if (rc < 0)\n"
        "                return -1;")
    leaks = [f for f in analyze_c_source(mutated)
             if f.rule == "NAT002" and f.detail == "leak:it@return"
             and f.symbol == "enc_value"]
    assert leaks, "deleted Py_DECREF(it) not caught"


def test_mutation_removing_gil_window_trips_nat006():
    # the bare BEGIN/END lines also appear in redwood_run_open since PR 17,
    # so the anchor carries the py_crc32c call line to stay unique
    src = _real_source()
    mutated = _mutate(
        src,
        "        Py_BEGIN_ALLOW_THREADS\n"
        "        crc = crc32c_sw(init, (const uint8_t *)data.buf,"
        " data.len);\n"
        "        Py_END_ALLOW_THREADS\n",
        "        crc = crc32c_sw(init, (const uint8_t *)data.buf,"
        " data.len);\n")
    hits = [f for f in analyze_c_source(mutated)
            if f.rule == "NAT006" and f.symbol == "py_crc32c"]
    assert any(f.detail == "gil:crc32c_sw" for f in hits)
    assert not [f for f in analyze_c_source(src)
                if f.rule == "NAT006" and f.symbol == "py_crc32c"]


def test_mutation_removing_count_guard_trips_nat007():
    src = _real_source()
    # the run-handle block parser carries the same guard since PR 17; the
    # anchor keeps the decode-side comment tail to stay unique
    mutated = _mutate(
        src,
        " * before it sizes the output list */\n"
        "    if (n > plen / 8)\n        goto corrupt;\n",
        " * before it sizes the output list */\n")
    hits = [f for f in analyze_c_source(mutated)
            if f.rule == "NAT007" and f.detail == "decoded:n"
            and f.symbol == "py_redwood_decode_block"]
    assert hits, "unvalidated decoded count not caught"
    assert not [f for f in analyze_c_source(src)
                if f.rule == "NAT007" and f.detail == "decoded:n"]


def test_mutation_removing_pyerr_check_trips_nat003():
    src = _real_source()
    mutated = _mutate(
        src,
        "        if (tid == (uint64_t)-1 && PyErr_Occurred())\n"
        "            return -1; /* registry id not an int-like: report, "
        "don't emit */\n",
        "")
    hits = [f.detail for f in analyze_c_source(mutated)
            if f.rule == "NAT003" and f.symbol == "enc_value"]
    assert any("PyLong_AsUnsignedLongLong:tid" in d for d in hits)
    assert not [f for f in analyze_c_source(src)
                if f.rule == "NAT003" and f.symbol == "enc_value"]


def test_mutation_bypassing_fast_conversion_trips_nat004():
    src = _real_source()
    mutated = _mutate(src, "PySequence_Fast_GET_ITEM(skipf, t)",
                      "PySequence_Fast_GET_ITEM(skip, t)")
    hits = [f for f in analyze_c_source(mutated)
            if f.rule == "NAT004" and f.detail == "unvalidated-fast:skip"]
    assert hits, "GET_ITEM on the raw argument not caught"
    assert not [f for f in analyze_c_source(src)
                if f.rule == "NAT004"
                and f.symbol == "py_encode_conflict_ranges"]


# ---------------------------------------------------------------------------
# enforcement: the real package is natlint-clean modulo the baseline
# ---------------------------------------------------------------------------

def test_package_is_natlint_clean():
    """The nat family over the default target set reports zero
    non-baselined violations and zero stale entries — same gate shape as
    test_package_is_flowlint_clean."""
    findings = flowlint.analyze_paths(flowlint.default_targets(),
                                      flowlint.active_rules("nat"))
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    new, stale = flowlint.apply_baseline(findings, baseline,
                                         families={"nat"})
    assert new == [], [f.message for f in new]
    assert stale == []


def test_nat_baseline_entries_are_documented_gil_exemptions():
    """The only grandfathered NAT findings are the two bounded redwood
    CRC loops, each with a documented reason (the generic FIXME gate lives
    in test_flowlint.py; this pins the natlint-specific policy: every
    exemption names why the unbounded-input concern does not apply)."""
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    nat = [e for e in baseline.entries if e["rule"].startswith("NAT")]
    assert nat, "expected the documented NAT006 redwood exemptions"
    for entry in nat:
        assert entry["rule"] == "NAT006"
        assert "REDWOOD_BLOCK_BYTES" in entry["reason"]
