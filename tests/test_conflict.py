"""Conflict engine tests: device kernel vs CPU oracle — identical decisions.

This is the oracle-test pattern the reference uses for its own conflict engine
(SkipList.cpp:1394 miniConflictSetTest cross-checks the bitmask against a
naive implementation): generate randomized batches, run both engines, assert
byte-identical abort decisions.
"""

import numpy as np
import pytest

from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnConflictInfo
from foundationdb_tpu.ops.conflict import DeviceConflictSet
from foundationdb_tpu.ops.conflict_oracle import OracleConflictSet
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


def small_device_set(**kw):
    kw.setdefault("capacity", 1024)
    kw.setdefault("txns", 64)
    kw.setdefault("reads_per_txn", 4)
    kw.setdefault("writes_per_txn", 4)
    return DeviceConflictSet(**kw)


def both():
    return small_device_set(), OracleConflictSet()


def txn(snap, reads=(), writes=()):
    return TxnConflictInfo(read_snapshot=snap,
                           read_ranges=list(reads), write_ranges=list(writes))


def check(dev, oracle, txns, version):
    got = dev.detect(txns, version)
    want = oracle.detect(txns, version)
    assert got == want, f"device={got} oracle={want} @v{version}"
    return got


# ---------------------------------------------------------------------------
# targeted semantics
# ---------------------------------------------------------------------------

def test_blind_writes_always_commit():
    dev, oracle = both()
    s = check(dev, oracle, [txn(0, writes=[(b"a", b"b")])], 100)
    assert s == [COMMITTED]
    # same key again, stale snapshot, still a blind write -> commits
    s = check(dev, oracle, [txn(0, writes=[(b"a", b"b")])], 200)
    assert s == [COMMITTED]


def test_read_write_conflict_and_snapshot_isolation():
    dev, oracle = both()
    check(dev, oracle, [txn(0, writes=[(b"k", b"k\x00")])], 100)
    # snapshot before the write -> conflict
    s = check(dev, oracle, [txn(50, reads=[(b"k", b"k\x00")])], 200)
    assert s == [CONFLICT]
    # snapshot after the write -> fine
    s = check(dev, oracle, [txn(150, reads=[(b"k", b"k\x00")])], 300)
    assert s == [COMMITTED]


def test_adjacent_ranges_do_not_conflict():
    dev, oracle = both()
    check(dev, oracle, [txn(0, writes=[(b"a", b"b")])], 100)
    s = check(dev, oracle, [txn(50, reads=[(b"b", b"c")])], 200)  # [a,b) vs [b,c)
    assert s == [COMMITTED]
    s = check(dev, oracle, [txn(50, reads=[(b"a\xff\xff", b"b")])], 300)
    assert s == [CONFLICT]  # strictly inside [a,b)


def test_intra_batch_earlier_txn_wins_and_aborted_writes_invisible():
    dev, oracle = both()
    batch = [
        txn(0, writes=[(b"x", b"x\x00")]),                       # commits
        txn(0, reads=[(b"x", b"x\x00")], writes=[(b"y", b"y\x00")]),  # conflicts with t0
        txn(0, reads=[(b"y", b"y\x00")]),                        # t1 aborted -> commits
    ]
    s = check(dev, oracle, batch, 100)
    assert s == [COMMITTED, CONFLICT, COMMITTED]


def test_intra_batch_long_chain():
    dev, oracle = both()
    # t_i reads k_{i-1}, writes k_i: alternating commit/conflict down the chain
    batch = [txn(0, writes=[(b"k0", b"k0\x00")])]
    for i in range(1, 20):
        batch.append(txn(0, reads=[(b"k%d" % (i - 1), b"k%d\x00" % (i - 1))],
                         writes=[(b"k%d" % i, b"k%d\x00" % i)]))
    s = check(dev, oracle, batch, 100)
    assert s == [COMMITTED if i % 2 == 0 else CONFLICT for i in range(20)]


def test_own_writes_do_not_conflict_with_own_reads():
    dev, oracle = both()
    s = check(dev, oracle,
              [txn(0, reads=[(b"a", b"b")], writes=[(b"a", b"b")])], 100)
    assert s == [COMMITTED]


def test_too_old():
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 1000)
    dev, oracle = both()
    check(dev, oracle, [txn(0, writes=[(b"a", b"b")])], 5000)
    # window floor is now 4000; snapshot 100 with reads -> too old
    s = check(dev, oracle, [txn(100, reads=[(b"z", b"z\x00")])], 6000)
    assert s == [TOO_OLD]
    # blind write with ancient snapshot is fine
    s = check(dev, oracle, [txn(100, writes=[(b"z", b"z\x00")])], 6100)
    assert s == [COMMITTED]


def test_window_gc_clamps_but_keeps_recent():
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 1000)
    dev, oracle = both()
    check(dev, oracle, [txn(0, writes=[(b"a", b"b")])], 100)
    check(dev, oracle, [txn(50, writes=[(b"m", b"n")])], 1050)
    # write@100 is now below the floor (50); snapshot 60 >= floor... but
    # clamped values make any read of [a,b) with snapshot < floor too old and
    # with snapshot in [floor, 100) conflict-equivalent. Snapshot 60 reads m:
    s = check(dev, oracle, [txn(60, reads=[(b"m", b"n")])], 1100)
    assert s == [CONFLICT]  # write@1050 > 60


def test_empty_batch_and_empty_txn():
    dev, oracle = both()
    assert check(dev, oracle, [], 100) == []
    s = check(dev, oracle, [txn(0)], 200)
    assert s == [COMMITTED]


def test_range_write_vs_point_read():
    dev, oracle = both()
    check(dev, oracle, [txn(0, writes=[(b"a", b"q")])], 100)
    s = check(dev, oracle, [txn(10, reads=[(b"m", b"m\x00")])], 200)
    assert s == [CONFLICT]
    s = check(dev, oracle, [txn(10, reads=[(b"q", b"q\x00")])], 300)
    assert s == [COMMITTED]


def test_chunking_preserves_batch_order_semantics():
    dev = DeviceConflictSet(capacity=1024, txns=8, reads_per_txn=2, writes_per_txn=2)
    oracle = OracleConflictSet()
    # 20 txns in one logical batch -> 3 device chunks; decisions must match a
    # single oracle batch exactly.
    batch = [txn(0, writes=[(b"c0", b"c0\x00")])]
    for i in range(1, 20):
        batch.append(txn(0, reads=[(b"c%d" % (i - 1), b"c%d\x00" % (i - 1))],
                         writes=[(b"c%d" % i, b"c%d\x00" % i)]))
    got = dev.detect(batch, 100)
    want = oracle.detect(batch, 100)
    assert got == want


# ---------------------------------------------------------------------------
# randomized parity (the oracle test)
# ---------------------------------------------------------------------------

def _random_key(rng, space):
    return space[rng.randint(0, len(space) - 1)]


def _random_range(rng, space):
    a, b = _random_key(rng, space), _random_key(rng, space)
    if a == b:
        return (a, a + b"\x00")
    return (min(a, b), max(a, b))


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_randomized_parity(seed):
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 500)
    rng = DeterministicRandom(seed)
    dev = small_device_set()
    oracle = OracleConflictSet()
    # small key space -> heavy contention
    space = [bytes([97 + i]) + bytes([97 + j]) for i in range(6) for j in range(6)]
    version = 0
    for _batch in range(25):
        version += rng.randint(1, 300)
        txns = []
        for _ in range(rng.randint(1, 30)):
            snap = max(0, version - rng.randint(0, 800))
            reads = [_random_range(rng, space) for _ in range(rng.randint(0, 3))]
            writes = [_random_range(rng, space) for _ in range(rng.randint(0, 3))]
            txns.append(txn(snap, reads, writes))
        check(dev, oracle, txns, version)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_randomized_parity_strided(seed):
    """The strided layout (static range->txn map; bench.py's configuration)
    must make decisions identical to the oracle — including txns with zero
    ranges, empty (b == e) read ranges (which still count for too-old), and
    chunking across multiple sub-batches."""
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 500)
    rng = DeterministicRandom(seed)
    dev = small_device_set(txns=8, reads_per_txn=3, writes_per_txn=3,
                           strided=True)
    oracle = OracleConflictSet()
    space = [bytes([97 + i]) + bytes([97 + j]) for i in range(6) for j in range(6)]
    version = 0
    for _batch in range(25):
        version += rng.randint(1, 300)
        txns = []
        for _ in range(rng.randint(1, 20)):  # > txns shape -> chunking
            snap = max(0, version - rng.randint(0, 800))
            reads = [_random_range(rng, space) for _ in range(rng.randint(0, 3))]
            writes = [_random_range(rng, space) for _ in range(rng.randint(0, 3))]
            if rng.randint(0, 9) == 0 and reads:
                reads[0] = (reads[0][0], reads[0][0])  # empty real range
            txns.append(txn(snap, reads, writes))
        check(dev, oracle, txns, version)


def test_strided_rejects_oversized_txn():
    from foundationdb_tpu.utils.errors import FDBError
    dev = small_device_set(txns=4, reads_per_txn=2, writes_per_txn=2,
                           strided=True)
    big = txn(0, reads=[(bytes([97 + i]), bytes([98 + i])) for i in range(3)])
    with pytest.raises(FDBError) as ei:
        dev.detect([big], 100)
    assert ei.value.name == "transaction_too_large"


@pytest.mark.parametrize("seed", [11, 12])
def test_randomized_parity_long_keys_and_prefixes(seed):
    rng = DeterministicRandom(seed)
    dev = small_device_set()
    oracle = OracleConflictSet()
    # nested/prefix-structured keys up to 24 bytes (exact-width boundary)
    space = []
    for _ in range(40):
        depth = rng.randint(1, 4)
        space.append(b"/".join(rng.random_bytes(rng.randint(1, 5)) for _ in range(depth))[:24])
    version = 0
    for _batch in range(15):
        version += rng.randint(1, 200)
        txns = [txn(max(0, version - rng.randint(0, 400)),
                    [_random_range(rng, space) for _ in range(rng.randint(0, 4))],
                    [_random_range(rng, space) for _ in range(rng.randint(0, 4))])
                for _ in range(rng.randint(1, 20))]
        check(dev, oracle, txns, version)


def test_long_key_truncation_never_false_commits():
    """Keys sharing a 24-byte prefix collapse on device; the collapse must
    round range ENDS up, so committed writes on long keys stay in history
    (a collapsed-to-empty write range would be a false commit)."""
    dev = small_device_set()
    long_a = b"p" * 28 + b"AAAA"
    long_b = b"p" * 28 + b"BBBB"  # distinct keys, same 24B prefix
    dev.detect([txn(0, writes=[(long_a, long_a + b"\x00")])], 100)
    s = dev.detect([txn(50, reads=[(long_b, long_b + b"\x00")])], 200)
    assert s == [CONFLICT]  # false conflict (collapse) — but never a miss
    s = dev.detect([txn(150, reads=[(long_b, long_b + b"\x00")])], 300)
    assert s == [COMMITTED]  # fresh snapshot sees past the write


def test_inverted_write_range_does_not_cancel_other_writes():
    """An inverted range (end < begin) must be inert: in the coverage
    prefix-sum a reversed -1/+1 delta pair would cancel a real write's
    coverage and drop it from history (false commit)."""
    dev, oracle = both()
    batch = [txn(0, writes=[(b"c", b"a")]),  # inverted
             txn(0, writes=[(b"b", b"d")])]
    check(dev, oracle, batch, 100)
    s = check(dev, oracle, [txn(50, reads=[(b"b", b"b\x00")])], 200)
    assert s == [CONFLICT]  # txn2's write survived the inverted neighbor


def test_empty_and_inverted_ranges_are_inert_intra_batch():
    dev, oracle = both()
    batch = [
        txn(0, writes=[(b"a", b"z")]),
        txn(0, reads=[(b"m", b"m")]),          # empty read inside [a,z)
        txn(0, reads=[(b"q", b"c")]),          # inverted read
        txn(0, writes=[(b"zx", b"c")], reads=[]),  # inverted write
        txn(0, reads=[(b"zx", b"zx\x00")]),  # inside inverted write only: inert
    ]
    s = check(dev, oracle, batch, 100)
    assert s == [COMMITTED, COMMITTED, COMMITTED, COMMITTED, COMMITTED]


def test_chunked_batch_uses_pre_batch_window_floor():
    """The MVCC floor advances once per logical batch: a txn in a later
    chunk must not see the floor moved by an earlier chunk."""
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 1000)
    dev = DeviceConflictSet(capacity=1024, txns=2, reads_per_txn=2, writes_per_txn=2)
    oracle = OracleConflictSet()
    batch = [txn(4900, writes=[(b"a", b"b")]),
             txn(4900, writes=[(b"c", b"d")]),
             txn(100, reads=[(b"zz", b"zz\x00")])]  # 3rd txn -> 2nd chunk
    got = dev.detect(batch, 5000)
    want = oracle.detect(batch, 5000)
    assert got == want == [COMMITTED, COMMITTED, COMMITTED]
    # after the batch, the floor HAS advanced (4000): now it is too old
    got = dev.detect([txn(100, reads=[(b"zz", b"zz\x00")])], 5100)
    want = oracle.detect([txn(100, reads=[(b"zz", b"zz\x00")])], 5100)
    assert got == want == [TOO_OLD]


def test_overflow_leaves_set_with_untruncated_state():
    tiny = DeviceConflictSet(capacity=64, txns=32, reads_per_txn=1, writes_per_txn=1)
    import pytest as _pytest
    with _pytest.raises(Exception):
        v = 0
        for i in range(20):
            v += 10
            tiny.detect([txn(0, writes=[(b"%04d" % (i * 31 + j), b"%04da" % (i * 31 + j))])
                         for j in range(31)], v)
    # the state the set holds must still satisfy its own invariant: nb <= K
    assert int(tiny._state["nb"]) <= 64


def test_state_survives_many_batches_with_gc():
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 1000)
    rng = DeterministicRandom(99)
    dev = small_device_set(capacity=512)
    oracle = OracleConflictSet()
    space = [b"k%02d" % i for i in range(30)]
    version = 0
    for _ in range(60):
        version += rng.randint(50, 200)
        txns = [txn(max(0, version - rng.randint(0, 1500)),
                    [_random_range(rng, space)],
                    [_random_range(rng, space)])
                for _ in range(rng.randint(1, 10))]
        check(dev, oracle, txns, version)
    # GC must keep the boundary count bounded by the live key space
    assert int(dev._state["nb"]) <= 2 * len(space) + 2


def test_full_capacity_merge_above_all_boundaries():
    """Regression: with the state exactly full (nb == K), committing a write
    above every stored boundary must still record BOTH endpoints. A surplus
    bisection step used to return K+1 for past-the-end queries, shifting the
    union slots right and silently dropping the write's end boundary
    (persistent false conflicts, or a broken sorted invariant)."""
    cs = DeviceConflictSet(capacity=4, txns=4, reads_per_txn=1,
                           writes_per_txn=1)
    version = 1000
    # fill the state to exactly K=4 boundaries ("", k10, k20, k30): adjacent
    # writes at distinct versions share interior boundaries
    for lo, hi in ((10, 20), (20, 30)):
        txns = [TxnConflictInfo(
            read_snapshot=version - 1, read_ranges=[],
            write_ranges=[(lo.to_bytes(4, "big"), hi.to_bytes(4, "big"))])]
        version += 1
        assert cs.detect(txns, version) == [COMMITTED]
    # commit a write above all boundaries while window GC coalesces the old
    # segments (large version jump keeps within int32 offsets)
    version += 6_000_000
    w = TxnConflictInfo(read_snapshot=version - 1, read_ranges=[],
                        write_ranges=[(int(100).to_bytes(4, "big"),
                                       int(200).to_bytes(4, "big"))])
    assert cs.detect([w], version) == [COMMITTED]
    # a read strictly above the write's end must NOT see it
    r_above = TxnConflictInfo(read_snapshot=version - 1,
                              read_ranges=[(int(200).to_bytes(4, "big"),
                                            int(300).to_bytes(4, "big"))],
                              write_ranges=[])
    # a read overlapping the write must conflict
    r_hit = TxnConflictInfo(read_snapshot=version - 1,
                            read_ranges=[(int(150).to_bytes(4, "big"),
                                          int(160).to_bytes(4, "big"))],
                            write_ranges=[])
    assert cs.detect([r_above, r_hit], version + 1) == [COMMITTED, CONFLICT]


@pytest.mark.parametrize("seed", [21, 22])
def test_randomized_parity_narrow_engine(seed):
    """A key_bytes=16 engine (5 limbs — the width the reference's own
    microbench keys need, SkipList.cpp setK 16-byte keys) must make decisions
    identical to the oracle for keys within its exact width, including the
    >16-byte conservative-collapse contract."""
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 500)
    rng = DeterministicRandom(seed)
    dev = small_device_set(key_bytes=16)
    oracle = OracleConflictSet()
    space = [b"............" + bytes([97 + i, 97 + j])  # setK-shaped 14B keys
             for i in range(5) for j in range(5)]
    version = 0
    for _batch in range(20):
        version += rng.randint(1, 300)
        txns = [txn(max(0, version - rng.randint(0, 800)),
                    [_random_range(rng, space) for _ in range(rng.randint(0, 3))],
                    [_random_range(rng, space) for _ in range(rng.randint(0, 3))])
                for _ in range(rng.randint(1, 30))]
        check(dev, oracle, txns, version)


def test_narrow_engine_long_key_collapse_is_conservative():
    dev = small_device_set(key_bytes=16)
    long_a = b"p" * 20 + b"AAAA"
    long_b = b"p" * 20 + b"BBBB"  # distinct, same 16B prefix
    assert dev.detect([txn(0, writes=[(long_a, long_a + b"\x00")])], 100) \
        == [COMMITTED]
    # reading the OTHER long key with a stale snapshot: collapsed prefix
    # must conservatively conflict (never false-commit)
    s = dev.detect([txn(50, reads=[(long_b, long_b + b"\x00")])], 200)
    assert s == [CONFLICT]


def test_rebase_preserves_conflicts_and_rejects_saturated_snapshots():
    """Rebase correctness at the extremes: after a >2^29 version jump the
    engine still catches a conflict whose versions were shifted (offsets
    stay exact), and a snapshot so stale its offset would SATURATE at the
    NEG sentinel is REJECTED (TOO_OLD) — a saturated snapshot compares
    equal to 'no version' and would silently miss every conflict in the
    window (hardened by the round-5 verify drive)."""
    dev = small_device_set()
    assert dev.detect([txn(0, writes=[(b"a", b"a\x00")])], 10) == [COMMITTED]
    # one-rebase jump: offsets shift but stay representable -> exact verdict
    s = dev.detect([txn(5, reads=[(b"a", b"a\x00")],
                        writes=[(b"b", b"b\x00")])], (1 << 30) + 77)
    assert s == [CONFLICT], s
    # two-rebase jump: snapshot 5's offset falls below NEG -> conservative
    # rejection, never a false commit
    dev2 = small_device_set()
    assert dev2.detect([txn(0, writes=[(b"a", b"a\x00")])], 10) == [COMMITTED]
    s = dev2.detect([txn(5, reads=[(b"a", b"a\x00")],
                         writes=[(b"b", b"b\x00")])], 1 << 31)
    assert s == [TOO_OLD], s


# ---------------------------------------------------------------------------
# deep parity fuzz (round-7 verify drive): richer workload shapes than the
# uniform-span fuzz above — variable-length keys, getRange-style prefix
# ranges, point reads, snapshot-read-exempt txns (reads the client never
# submits as conflict ranges, i.e. blind writes), empty ranges — over
# >= 1000 seeded batches total, byte-identical to the oracle.
# ---------------------------------------------------------------------------

def _fuzz_key(rng):
    # variable-length keys over a 3-letter alphabet: dense prefix structure,
    # so prefix ranges nest and partially overlap constantly
    return bytes(rng.randint(97, 99) for _ in range(rng.randint(1, 5)))


def _fuzz_range(rng):
    a = _fuzz_key(rng)
    kind = rng.randint(0, 9)
    if kind < 4:  # point access: [k, k+\x00)
        return (a, a + b"\x00")
    if kind < 7:  # getRange(prefix): [k, k+\xff) — covers all children
        return (a, a + b"\xff")
    b = _fuzz_key(rng)  # arbitrary span between two keys
    if a == b:
        return (a, a + b"\x00")
    return (min(a, b), max(a, b))


def _fuzz_txn(rng, version):
    snap = max(0, version - rng.randint(0, 900))
    if rng.randint(0, 5) == 0:
        # snapshot-read txn: its reads are EXEMPT from conflict checking,
        # so the client submits only write ranges (blind write on device)
        return txn(snap, [], [_fuzz_range(rng) for _ in range(rng.randint(1, 3))])
    reads = [_fuzz_range(rng) for _ in range(rng.randint(0, 3))]
    writes = [_fuzz_range(rng) for _ in range(rng.randint(0, 3))]
    if rng.randint(0, 19) == 0 and reads:
        reads[0] = (reads[0][0], reads[0][0])  # empty range: inert but real
    return txn(snap, reads, writes)


@pytest.mark.parametrize("seed", [31, 32, 33, 34])
def test_deep_parity_fuzz(seed):
    """>= 1000 batches across the seed set (4 x 260), one long-lived engine
    pair per seed (state carries across batches: history-vs-intra interplay
    is the hard part of the scan kernel)."""
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 600)
    rng = DeterministicRandom(seed)
    dev = small_device_set()
    oracle = OracleConflictSet()
    version = 0
    for _batch in range(260):
        version += rng.randint(1, 250)
        txns = [_fuzz_txn(rng, version) for _ in range(rng.randint(1, 24))]
        check(dev, oracle, txns, version)


def test_capped_rounds_fallback_parity():
    """With the sandwich capped at 1 round, deep dependency chains cannot
    converge on device; the host-exact fallback must still produce
    oracle-identical statuses (fresh sets per batch: unconverged merges are
    conservative, so only same-batch decisions are comparable)."""
    KNOBS.set("CONFLICT_INTRA_ROUNDS", 1)
    rng = DeterministicRandom(77)
    for trial in range(6):
        dev = small_device_set()
        oracle = OracleConflictSet()
        if trial == 0:
            # depth-20 chain: the worst case for a capped fixpoint
            batch = [txn(0, writes=[(b"k0", b"k0\x00")])]
            for i in range(1, 20):
                batch.append(txn(0, reads=[(b"k%d" % (i - 1), b"k%d\x00" % (i - 1))],
                                 writes=[(b"k%d" % i, b"k%d\x00" % i)]))
        else:
            batch = [_fuzz_txn(rng, 100) for _ in range(rng.randint(8, 30))]
        check(dev, oracle, batch, 100)


# ---------------------------------------------------------------------------
# CI smoke: the scan kernel vs the legacy fixpoint kernel (A/B on the knob),
# and the serving jaxpr contains NO unbounded while_loop
# ---------------------------------------------------------------------------

def test_scan_matches_legacy_kernel():
    KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 600)
    KNOBS.set("CONFLICT_INTRA_MODE", "legacy")
    legacy = small_device_set()
    KNOBS.set("CONFLICT_INTRA_MODE", "scan")
    scan = small_device_set()
    oracle = OracleConflictSet()
    rng = DeterministicRandom(55)
    version = 0
    for _batch in range(20):
        version += rng.randint(1, 250)
        txns = [_fuzz_txn(rng, version) for _ in range(rng.randint(1, 24))]
        a = legacy.detect(txns, version)
        b = scan.detect(txns, version)
        want = oracle.detect(txns, version)
        assert a == b == want, (a, b, want)


def test_serving_jaxpr_has_no_while_loop():
    """The tentpole's structural guarantee: the serving detect path lowers to
    bounded control flow only (scan/cond) — an unbounded `while` primitive
    would reintroduce the data-dependent fixpoint the overhaul removed. The
    legacy escape hatch, by contrast, must still carry its while_loop."""
    import jax
    from foundationdb_tpu.ops import conflict as C
    dev = small_device_set()
    state = C.init_state(dev.shapes)
    batch = dev.encoder.encode_batch(
        [txn(0, reads=[(b"a", b"b")], writes=[(b"c", b"d")])], 100)
    life = KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS

    def step(mode):
        return str(jax.make_jaxpr(
            lambda s, b: C.conflict_step(s, b, shapes=dev.shapes,
                                         max_write_life=life,
                                         intra_mode=mode))(state, batch))

    serving = step("scan")
    assert "while[" not in serving, "unbounded fixpoint back in serving path"
    assert "scan[" in serving  # the bounded sandwich is there
    assert "while[" in step("legacy")
