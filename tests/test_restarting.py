"""Whole-cluster restart specs (the reference's tests/restarting/*.txt).

The restarting tests are the only specs the reference runs as TWO fdbserver
invocations: run half the workload, kill every process at once, restart the
binaries on the surviving on-disk state, finish the workload, and check the
invariant. Here both halves share one simulation — RecoverableCluster.
restart_from_disk() kills every cluster process simultaneously (unsynced
file tails torn, like a power loss), the processes reboot onto their durable
files, and the cluster must re-elect, re-recover, and serve the same data.
"""

import pytest

from foundationdb_tpu.core.sim import KillType  # noqa: F401 — doc pointer
from foundationdb_tpu.testing.workloads import (
    ConsistencyCheckWorkload, CycleWorkload, quiet_database)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


async def _await_recovered(c, db, max_polls: int = 600):
    """Wait until some CC reaches accepting_commits and a transaction lands
    (run_spec's quiesce probe)."""
    for _ in range(max_polls):
        if c.current_cc() is not None:
            try:
                async def probe(tr):
                    await tr.get(b"\x00restart-probe")
                await db.transact(probe, max_retries=50)
                return
            except FDBError:
                pass
        await c.loop.delay(0.5)
    raise AssertionError("cluster never re-recovered after restart")


def _restart_spec(seed: int, engine: str, tmp_path, n_replicas: int = 1,
                  n_storage_workers: int = None, half: float = 12.0):
    """Half the workload -> whole-cluster restart from disk -> second half
    -> quiesce -> invariant checks."""
    from foundationdb_tpu.server.cluster import RecoverableCluster
    from foundationdb_tpu.utils.rng import DeterministicRandom

    KNOBS.set("STORAGE_ENGINE", engine)
    KNOBS.set("SSD_DATA_DIR", str(tmp_path))
    rng = DeterministicRandom(seed)
    c = RecoverableCluster(seed=rng.randint(0, 1 << 30), n_workers=5,
                           n_proxies=2, n_tlogs=2, n_storage=2,
                           n_replicas=n_replicas,
                           n_storage_workers=n_storage_workers)
    db = c.database()
    cyc = CycleWorkload()
    cons = ConsistencyCheckWorkload()

    async def scenario():
        await db.refresh(max_wait=120.0)

        # ---- first half ----
        cyc.init(c, rng.fork(), c.loop.now() + half)
        cons.init(c, rng.fork(), c.loop.now() + half)
        await cyc.setup(db)
        await cyc.start(db)
        first_half = cyc.rotations
        assert first_half > 0, "no progress before the restart"
        # let the pipeline make the committed ring durable before pulling
        # the plug (a torn unsynced tail is fine; an empty disk is not)
        await quiet_database(c, db)

        # ---- whole-cluster restart ----
        c.restart_from_disk()
        await _await_recovered(c, db)

        # ---- second half ----
        cyc.stop_at = c.loop.now() + half
        await cyc.start(db)
        assert cyc.rotations > first_half, "no progress after the restart"

        # ---- quiesce + checks ----
        c.net.heal()
        c.net.reboot_dead([p.address for p in c.cluster_procs()])
        await quiet_database(c, db)
        await cyc.check(db)
        await cons.check(db)

    c.run(c.loop.spawn(scenario()), max_time=600_000.0)
    return cyc


def test_restart_from_disk_memory_engine(tmp_path):
    cyc = _restart_spec(701, "memory", tmp_path)
    assert cyc.rotations > 0


def test_restart_from_disk_ssd_engine(tmp_path):
    cyc = _restart_spec(702, "ssd", tmp_path)
    assert cyc.rotations > 0


def test_restart_from_disk_redwood_engine(tmp_path):
    # shrink the engine budgets so the first half actually flushes runs and
    # compacts them BEFORE the plug is pulled — the restart then exercises
    # run-file recovery + WAL replay, not just an empty-levels WAL replay
    KNOBS.set("REDWOOD_MEMTABLE_BYTES", 4_096)
    KNOBS.set("REDWOOD_BLOCK_BYTES", 512)
    KNOBS.set("REDWOOD_COMPACTION_FAN_IN", 2)
    cyc = _restart_spec(704, "redwood", tmp_path)
    assert cyc.rotations > 0


@pytest.mark.slow
def test_restart_from_disk_double_replication(tmp_path):
    """Restart with replicated teams: both replicas of every shard recover
    from disk and the ConsistencyCheck proves they re-converge."""
    cyc = _restart_spec(703, "memory", tmp_path, n_replicas=2,
                        n_storage_workers=4, half=15.0)
    assert cyc.rotations > 0
