"""Spec-driven fault-cocktail runs: Cycle + clogging + attrition, seeded.

Reference: tests/fast/CycleTest.txt (Cycle paired with RandomClogging +
Attrition under buggified knobs) and tests/slow/SwizzledCycleTest.txt; the
determinism contract of testing.rst — a failing seed replays identically.
"""

import pytest

from foundationdb_tpu.testing import (
    AttritionWorkload, CycleWorkload, RandomCloggingWorkload,
    SwizzleCloggingWorkload, run_spec)
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_cycle_with_clogging_and_attrition(seed):
    r = run_spec(seed, duration=45.0)
    assert r.rotations > 0


def test_swizzled_cycle():
    r = run_spec(7, workloads=[CycleWorkload(), SwizzleCloggingWorkload()],
                 duration=40.0)
    assert r.rotations > 0


def test_spec_runs_are_deterministic():
    """Same seed => identical outcome (rotation count, epochs, virtual end
    time) — the replayability contract the whole test strategy rests on."""
    a = run_spec(55, duration=30.0)
    KNOBS.reset()
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    b = run_spec(55, duration=30.0)
    assert (a.rotations, a.epochs, a.elapsed) == (b.rotations, b.epochs, b.elapsed)
