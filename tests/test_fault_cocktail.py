"""Spec-driven fault-cocktail runs: Cycle + clogging + attrition, seeded.

Reference: tests/fast/CycleTest.txt (Cycle paired with RandomClogging +
Attrition under buggified knobs) and tests/slow/SwizzledCycleTest.txt; the
determinism contract of testing.rst — a failing seed replays identically.
"""

import pytest

from foundationdb_tpu.testing import (
    AttritionWorkload, CycleWorkload, RandomCloggingWorkload,
    SwizzleCloggingWorkload, run_spec)
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_cycle_with_clogging_and_attrition(seed):
    r = run_spec(seed, duration=45.0)
    assert r.rotations > 0


def test_swizzled_cycle():
    r = run_spec(7, workloads=[CycleWorkload(), SwizzleCloggingWorkload()],
                 duration=40.0)
    assert r.rotations > 0


def test_spec_runs_are_deterministic():
    """Same seed => identical outcome (rotation count, epochs, virtual end
    time) — the replayability contract the whole test strategy rests on."""
    a = run_spec(55, duration=30.0)
    KNOBS.reset()
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    b = run_spec(55, duration=30.0)
    assert (a.rotations, a.epochs, a.elapsed) == (b.rotations, b.epochs, b.elapsed)


def test_cycle_cocktail_with_sharded_backend():
    """The full recruited cluster running the MESH-SHARDED conflict engine
    (8-device CPU mesh stands in for the TPU slice): Cycle + clogging +
    attrition stays serializable, recoveries re-instantiate the sharded
    engine (VERDICT r2 item 2: the sharded engine as a cluster component,
    not a demo)."""
    KNOBS.set("CONFLICT_BACKEND", "sharded")
    KNOBS.set("CONFLICT_CPU_FALLBACK", "jax")  # exercise the JAX serving path in CI
    # small static shapes: compile once (cached across recoveries)
    KNOBS.set("CONFLICT_BATCH_TXNS", 16)
    KNOBS.set("CONFLICT_BATCH_READS_PER_TXN", 2)
    KNOBS.set("CONFLICT_BATCH_WRITES_PER_TXN", 2)
    KNOBS.set("CONFLICT_STATE_CAPACITY", 2048)
    try:
        r = run_spec(17, duration=30.0, buggify=False)
        assert r.rotations > 0
    finally:
        KNOBS.reset()


def test_cycle_cocktail_with_device_backend():
    """The recruited cluster serving live commits through the DEVICE engine
    (single-device JAX kernel; CPU backend in CI, TPU in deployment), with
    the pipelined resolver drain path: Cycle + clogging + attrition stays
    serializable and recoveries re-instantiate the engine mid-workload
    (VERDICT r4 item 2: the TPU engine on the served end-to-end path, fault
    family included)."""
    KNOBS.set("CONFLICT_BACKEND", "device")
    KNOBS.set("CONFLICT_CPU_FALLBACK", "jax")  # exercise the JAX serving path in CI
    KNOBS.set("CONFLICT_BATCH_TXNS", 16)
    KNOBS.set("CONFLICT_BATCH_READS_PER_TXN", 2)
    KNOBS.set("CONFLICT_BATCH_WRITES_PER_TXN", 2)
    KNOBS.set("CONFLICT_STATE_CAPACITY", 2048)
    try:
        r = run_spec(23, duration=30.0, buggify=False)
        assert r.rotations > 0
    finally:
        KNOBS.reset()
