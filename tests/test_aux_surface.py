"""Options codegen, transaction options, sampling profiler, transport TLS.

Reference: fdbclient/vexillographer/fdb.options (+ the generated binding
option surfaces), flow/Profiler.actor.cpp (sampling profiler),
FDBLibTLS/* (mutual TLS with verify_peers clauses).
"""

import os
import subprocess
import time

import pytest

from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


# ---------------------------------------------------------------- options

def test_options_codegen_is_stable_and_complete():
    """The checked-in fdboptions.py is exactly what the generator emits,
    and carries the public codes bindings rely on."""
    from foundationdb_tpu.utils import option_spec
    gen = option_spec.generate_source()
    path = os.path.join(os.path.dirname(option_spec.__file__),
                        "fdboptions.py")
    with open(path) as f:
        assert f.read() == gen, \
            "fdboptions.py is stale: rerun python -m foundationdb_tpu.utils.option_spec"
    from foundationdb_tpu.utils.fdboptions import (
        DatabaseOption, NetworkOption, StreamingMode, TransactionOption)
    assert int(TransactionOption.timeout) == 500
    assert int(TransactionOption.retry_limit) == 501
    assert int(TransactionOption.size_limit) == 503
    assert int(DatabaseOption.transaction_timeout) == 500
    assert int(NetworkOption.tls_verify_peers) == 41
    assert int(StreamingMode.want_all) == -2


def test_transaction_options_honored():
    from foundationdb_tpu.utils.fdboptions import TransactionOption
    c = SimCluster(seed=71)
    db = c.database()

    async def t():
        # size_limit: a txn over its own limit is rejected client-side
        tr = db.create_transaction()
        tr.set_option(TransactionOption.size_limit, 64)
        tr.set(b"k", b"v" * 256)
        with pytest.raises(FDBError) as ei:
            await tr.commit()
        assert ei.value.name == "transaction_too_large"
        # retry_limit: on_error gives up after N retries
        tr = db.create_transaction()
        tr.set_option(TransactionOption.retry_limit, 2)
        err = FDBError("not_committed")
        await tr.on_error(err)
        await tr.on_error(err)
        with pytest.raises(FDBError):
            await tr.on_error(err)
        # unknown option code is rejected, known advisory ones accepted
        tr = db.create_transaction()
        with pytest.raises(FDBError):
            tr.set_option(99999)
        tr.set_option(TransactionOption.causal_read_risky)
        # timeout: a commit against nothing reachable times out instead of
        # hanging (GRV goes to a dead proxy)
        dead = db.create_transaction()
        dead.set_option(TransactionOption.timeout, 500)
        c.net.kill(c.proxy_procs[0].address)
        dead.set(b"x", b"y")
        with pytest.raises(FDBError) as ei:
            await dead.commit()
        assert ei.value.name in ("timed_out", "commit_unknown_result",
                                 "request_maybe_delivered")

    c.run(c.loop.spawn(t()), max_time=600.0)


# ---------------------------------------------------------------- profiler

def test_sampling_profiler_finds_the_hot_function():
    from foundationdb_tpu.utils.profiler import SamplingProfiler

    def hot_spin(deadline):
        x = 0
        while time.monotonic() < deadline:
            x += 1
        return x

    p = SamplingProfiler(interval=0.001)
    p.start()
    hot_spin(time.monotonic() + 0.4)
    report = p.stop()
    assert p.total_samples > 20
    hottest = p.hottest_functions(top=3)
    assert any("hot_spin" in label for label, _n in hottest), hottest
    assert report and report[0][1] >= 1
    p.trace_report()  # must not raise


# ---------------------------------------------------------------- TLS

def _make_certs(tmp, ca_cn="fdbtpu-ca"):
    def run(*args):
        subprocess.run(args, check=True, capture_output=True)
    ca_key, ca_crt = tmp / "ca.key", tmp / "ca.crt"
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", f"/CN={ca_cn}")
    out = {}
    for name in ("server", "client"):
        key, csr, crt = tmp / f"{name}.key", tmp / f"{name}.csr", tmp / f"{name}.crt"
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={name}")
        run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
            "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
            "-days", "1")
        out[name] = (str(crt), str(key))
    return str(ca_crt), out


def test_transport_tls_mutual_auth_and_verify_peers(tmp_path):
    from foundationdb_tpu.net.tls import TLSConfig
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    import socket

    try:
        ca, certs = _make_certs(tmp_path)
    except (FileNotFoundError, subprocess.CalledProcessError):
        pytest.skip("openssl unavailable")

    def free_addr():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        a = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        return a

    loop = RealEventLoop()
    server_tls = TLSConfig(*certs["server"], ca_path=ca,
                           verify_peers="Check.Valid=1,I.CN=fdbtpu-ca")
    client_tls = TLSConfig(*certs["client"], ca_path=ca)
    srv = NetTransport(loop, free_addr(), tls=server_tls)
    cli = NetTransport(loop, free_addr(), tls=client_tls)
    srv.start()
    cli.start()
    srv.process.register(7001, lambda req, reply: reply.send(req + b"!"))

    from foundationdb_tpu.core.sim import Endpoint

    async def roundtrip():
        return await cli.request(cli.process, Endpoint(srv.address, 7001),
                                 b"hello")
    got = loop.run_future(loop.spawn(roundtrip()), max_time=30.0)
    assert got == b"hello!"

    # an un-authenticated (wrong-CA) client is rejected by the handshake
    (tmp_path / "other").mkdir(exist_ok=True)
    ca2, certs2 = _make_certs(tmp_path / "other", ca_cn="evil-ca")
    bad = NetTransport(loop, free_addr(),
                       tls=TLSConfig(*certs2["client"], ca_path=ca2))
    bad.start()

    async def bad_roundtrip():
        return await loop.timeout(
            bad.request(bad.process, Endpoint(srv.address, 7001), b"x"), 5.0)
    with pytest.raises(FDBError):
        loop.run_future(loop.spawn(bad_roundtrip()), max_time=30.0)

    # verify_peers clause mismatch fails even with a VALID chain
    assert not TLSConfig(*certs["server"], ca_path=ca,
                         verify_peers="Check.Valid=1,S.CN=somebody-else") \
        .check_peer({"subject": ((("commonName", "client"),),),
                     "issuer": ((("commonName", "fdbtpu-ca"),),)})
    assert TLSConfig(*certs["server"], ca_path=ca,
                     verify_peers="Check.Valid=1,S.CN=client") \
        .check_peer({"subject": ((("commonName", "client"),),),
                     "issuer": ((("commonName", "fdbtpu-ca"),),)})

    cli.close()
    bad.close()
    srv.close()
