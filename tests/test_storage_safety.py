"""Regression tests for the storage server's safety mechanisms against a
scripted TLog:

1. Durability is clamped by the log system's known_committed_version: a
   single TLog's peeks advance the pull cursor through never-fully-acked
   versions, and those must never reach the durable engine (they can be
   rolled back by a recovery, and rollback below durable is fatal).
2. Rollback below the durable version kills the storage process (loud,
   contained) instead of silently clamping and serving uncommitted data.
3. A fetchKeys splice parks the update loop before snapshotting: a peek
   reply already in flight when the gate is set is discarded, so the splice
   cannot race ingestion past its snapshot version (the DD-liveness defect
   where VersionedMap's version-order guard failed the move round after
   round).

Reference: storageserver.actor.cpp update/updateStorage/fetchKeys,
TLogPeekReply knownCommittedVersion semantics.
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.core.future import Future
from foundationdb_tpu.core.sim import SimNetwork
from foundationdb_tpu.server.interfaces import (
    AddShardRequest, GetKeyValuesReply, LogEpoch, SetLogSystemRequest,
    TLogPeekReply, Token)
from foundationdb_tpu.server.storage import StorageServer
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom
from foundationdb_tpu.utils.types import Mutation, MutationType


def _set(k, v):
    return Mutation(MutationType.SET_VALUE, k, v)


class ScriptedTLog:
    """A fake TLog process: serves a fixed message list with a controllable
    known_committed_version and an optional gate delaying one peek reply."""

    def __init__(self, process, messages, end, kc):
        self.process = process
        self.messages = messages  # [(version, [Mutation])]
        self.end = end
        self.kc = kc
        self.hold_next_peek: Future | None = None
        process.register(Token.TLOG_PEEK, self._on_peek)
        process.register(Token.TLOG_POP, lambda req, reply: reply.send(None))

    def _on_peek(self, req, reply):
        self.process.spawn(self._peek(req, reply), "scriptedPeek")

    async def _peek(self, req, reply):
        if self.hold_next_peek is not None:
            gate, self.hold_next_peek = self.hold_next_peek, None
            await gate
        msgs = [(v, list(muts)) for v, muts in self.messages
                if v >= req.begin]
        reply.send(TLogPeekReply(messages=msgs, end=self.end, popped=0,
                                 known_committed_version=self.kc))


def _harness(seed=1):
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(seed))
    return loop, net


def test_durability_stalls_at_known_committed():
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 10)
    loop, net = _harness()
    tlog_proc = net.new_process("tlog:0")
    msgs = [(v, [_set(b"k%03d" % v, b"v")]) for v in range(1, 201)]
    tlog = ScriptedTLog(tlog_proc, msgs, end=201, kc=100)
    ss_proc = net.new_process("ss:0")
    ss = StorageServer(ss_proc, tag=0, tlog_addrs=["tlog:0"])

    async def t():
        await loop.delay(5.0)
        # pull cursor reached the end, but durability stopped at kc
        assert ss.version.get() == 200
        assert ss.durable_version == 100, ss.durable_version
        # acks catch up -> durability resumes to peek_begin - window
        tlog.kc = 200
        await loop.delay(5.0)
        assert ss.durable_version == 190, ss.durable_version

    loop.run_future(loop.spawn(t()), max_time=600.0)


def test_rollback_below_durable_kills_storage_process():
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 10)
    loop, net = _harness()
    tlog_proc = net.new_process("tlog:0")
    msgs = [(v, [_set(b"k%03d" % v, b"v")]) for v in range(1, 201)]
    ScriptedTLog(tlog_proc, msgs, end=201, kc=200)
    ss_proc = net.new_process("ss:0")
    ss = StorageServer(ss_proc, tag=0, tlog_addrs=["tlog:0"])
    client = net.new_process("client:0")

    async def t():
        await loop.delay(5.0)
        assert ss.durable_version == 190
        # a recovery claims rollback below what this SS made durable
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.utils.errors import FDBError
        req = SetLogSystemRequest(
            epochs=[LogEpoch(begin=0, end=None, addrs=["tlog:0"])],
            rollback_to=150, recovery_count=ss.recovery_count + 1)
        with pytest.raises(FDBError) as ei:
            await net.request(client,
                              Endpoint("ss:0", Token.STORAGE_SET_LOGSYSTEM),
                              req)
        # the reply races the kill: either the explicit internal_error or a
        # broken promise from the dying process — never a silent success
        assert ei.value.name in ("internal_error", "broken_promise",
                                 "request_maybe_delivered")
        assert not ss_proc.alive, "storage process must be dead"

    loop.run_future(loop.spawn(t()), max_time=600.0)


def test_fetchkeys_discards_in_flight_peek():
    """The splice must park ingestion BEFORE snapshotting: a peek held in
    flight across the gate-set is discarded, not applied at versions above
    the snapshot point."""
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 10)
    loop, net = _harness()
    tlog_proc = net.new_process("tlog:0")
    msgs = [(v, [_set(b"a%03d" % v, b"v")]) for v in range(1, 51)]
    tlog = ScriptedTLog(tlog_proc, msgs, end=51, kc=50)

    # source storage server for the snapshot
    src_proc = net.new_process("src:0")
    rows = [(b"m%02d" % i, b"s") for i in range(5)]

    def on_get_kv(req, reply):
        reply.send(GetKeyValuesReply(data=list(rows), more=False,
                                     version=req.version))
    src_proc.register(Token.STORAGE_GET_KEY_VALUES, on_get_kv)

    ss_proc = net.new_process("ss:0")
    ss = StorageServer(ss_proc, tag=0, tlog_addrs=["tlog:0"],
                       shard_ranges=[(b"a", b"b")])
    client = net.new_process("client:0")

    async def t():
        from foundationdb_tpu.core.sim import Endpoint
        await loop.delay(2.0)
        assert ss.version.get() == 50
        # hold the NEXT peek in flight, then extend the log so the held
        # reply carries versions beyond the splice snapshot
        gate = Future()
        tlog.hold_next_peek = gate
        await loop.delay(1.0)  # the update loop is now parked in the peek
        tlog.messages.extend(
            (v, [_set(b"a%03d" % v, b"v")]) for v in range(51, 61))
        tlog.end = 61
        fut = net.request(client, Endpoint("ss:0", Token.STORAGE_ADD_SHARD),
                          AddShardRequest(begin=b"m", end=b"n",
                                          source="src:0", fence_version=40))
        await loop.delay(1.0)
        gate._set(None)  # release the held peek WHILE the splice waits
        c0 = await fut  # splice must complete (no internal_error)
        assert c0 == 50, c0
        await loop.delay(2.0)
        # ingestion resumed past the splice point and nothing was lost
        assert ss.version.get() == 60
        for k, v in rows:
            assert ss.data.get(k, 60) == b"s", k
        assert ss.data.get(b"a060", 60) == b"v"

    loop.run_future(loop.spawn(t()), max_time=600.0)


def test_keyservers_private_mutation_fences_moved_shard():
    """Regression for the version-unfenced shard handoff: DD's final
    metadata commit reroutes a moved range's writes to the new team, but the
    old owner only learns of the move from a one-way SET_SHARDS push — and
    its version keeps advancing past the move through empty peek ranges, so
    `_wait_for_version` passes and it serves STALE values at post-move read
    versions (the seed-3 serializability violation). The proxy now
    broadcasts keyServers mutations to every storage tag (the reference's
    private serverKeys mutations, ApplyMetadataMutation.h): the old owner
    sees the move in its OWN stream at the commit version and fences the
    range from that version on, until a re-adding fetch re-copies the data.
    """
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.server import systemdata
    from foundationdb_tpu.utils.errors import FDBError

    # wide MVCC window so pre-move read versions stay readable
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 1000)
    loop, net = _harness()
    tlog_proc = net.new_process("tlog:0")
    msgs = [(v, [_set(b"a%03d" % v, b"v%03d" % v)]) for v in range(1, 30)]
    # v=30: DD moves [a, b) to tag 1 — the keyServers change arrives in
    # THIS server's (tag 0) stream via the proxy broadcast. No further
    # messages: the log's `end` advances the version the same way the
    # live cluster's empty peek ranges did.
    msgs.append((30, [_set(systemdata.keyservers_key(b"a"),
                           systemdata.encode_tags([1]))]))
    ScriptedTLog(tlog_proc, msgs, end=51, kc=50)

    src_proc = net.new_process("src:0")
    rows = [(b"a%03d" % v, b"fresh%03d" % v) for v in range(1, 6)]

    def on_get_kv(req, reply):
        reply.send(GetKeyValuesReply(data=list(rows), more=False,
                                     version=req.version))
    src_proc.register(Token.STORAGE_GET_KEY_VALUES, on_get_kv)

    ss_proc = net.new_process("ss:0")
    ss = StorageServer(ss_proc, tag=0, tlog_addrs=["tlog:0"],
                       shard_ranges=[(b"a", b"b")])
    client = net.new_process("client:0")

    async def rd(key, version):
        from foundationdb_tpu.server.interfaces import GetValueRequest
        return await net.request(
            client, Endpoint("ss:0", Token.STORAGE_GET_VALUE),
            GetValueRequest(key=key, version=version))

    async def t():
        await loop.delay(2.0)
        assert ss.version.get() == 50  # advanced PAST the move version
        # pre-move read versions still serve (MVCC history is intact)
        assert (await rd(b"a010", 25)).value == b"v010"
        # post-move read versions bounce instead of serving stale data,
        # even though shard_ranges still lists the range
        for rv in (30, 40, 50):
            with pytest.raises(FDBError) as ei:
                await rd(b"a010", rv)
            assert ei.value.name == "wrong_shard_server", rv
        # the range moves BACK: the fetch re-copies the data at c0 and
        # lifts the fence — reads serve the fresh copy again
        c0 = await net.request(
            client, Endpoint("ss:0", Token.STORAGE_ADD_SHARD),
            AddShardRequest(begin=b"a", end=b"b", source="src:0",
                            fence_version=45))
        assert c0 == 50, c0
        assert (await rd(b"a003", 50)).value == b"fresh003"
        assert ss._revoked == [], ss._revoked

    loop.run_future(loop.spawn(t()), max_time=600.0)


def test_set_shards_prunes_unlisted_revocations():
    """The authoritative layout push drops revocations for ranges it no
    longer lists (the ownership check enforces those from then on), keeping
    the fence list bounded across repeated moves."""
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.server import systemdata
    from foundationdb_tpu.server.interfaces import SetShardsRequest
    from foundationdb_tpu.utils.errors import FDBError

    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 10)
    loop, net = _harness()
    tlog_proc = net.new_process("tlog:0")
    msgs = [(v, [_set(b"a%03d" % v, b"v")]) for v in range(1, 20)]
    msgs.append((20, [_set(systemdata.keyservers_key(b"a"),
                           systemdata.encode_tags([1]))]))
    ScriptedTLog(tlog_proc, msgs, end=31, kc=30)
    ss_proc = net.new_process("ss:0")
    ss = StorageServer(ss_proc, tag=0, tlog_addrs=["tlog:0"],
                       shard_ranges=[(b"a", b"b"), (b"c", b"d")])
    client = net.new_process("client:0")

    async def t():
        await loop.delay(2.0)
        assert ss._revoked == [(b"a", b"b", 20)], ss._revoked
        # the push removes [a, b) from this server's layout: the revocation
        # is pruned and the ownership check takes over
        await net.request(
            client, Endpoint("ss:0", Token.STORAGE_SET_SHARDS),
            SetShardsRequest(shard_ranges=[(b"c", b"d")]))
        assert ss._revoked == [], ss._revoked
        from foundationdb_tpu.server.interfaces import GetValueRequest
        with pytest.raises(FDBError) as ei:
            await net.request(
                client, Endpoint("ss:0", Token.STORAGE_GET_VALUE),
                GetValueRequest(key=b"a010", version=25))
        assert ei.value.name == "wrong_shard_server"

        # a fence can OVER-cover (the server revokes from its coarse served
        # range, not the moved shard's exact bounds): the push lifts fences
        # at/below its as_of_version — that layout accounts for the move —
        # but a delayed STALE push (older as_of_version) must not lift a
        # newer fence even when it lists the range
        async def push(av):
            await net.request(
                client, Endpoint("ss:0", Token.STORAGE_SET_SHARDS),
                SetShardsRequest(shard_ranges=[(b"c", b"d")],
                                 as_of_version=av))
        ss._revoked = [(b"c", b"d", 20)]
        await push(19)
        assert ss._revoked == [(b"c", b"d", 20)], ss._revoked
        await push(20)
        assert ss._revoked == [], ss._revoked

    loop.run_future(loop.spawn(t()), max_time=600.0)


def test_cursor_mid_retry_observes_new_epochs():
    """VERDICT r4 regression: a recovery that installs a new epoch list while
    PeekCursor.get_more() is mid-retry against a dead TLog must be observed
    on the cursor's NEXT attempt — not only between get_more() calls. The
    reference cursor routes every attempt through the live log-system config
    (LogSystemPeekCursor.actor.cpp)."""
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 10)
    loop, net = _harness()
    tlog_proc = net.new_process("tlog:0")
    msgs = [(v, [_set(b"k%03d" % v, b"v")]) for v in range(1, 51)]
    ScriptedTLog(tlog_proc, msgs, end=51, kc=50)
    ss_proc = net.new_process("ss:0")
    ss = StorageServer(ss_proc, tag=0, tlog_addrs=["tlog:0"])
    client = net.new_process("client:0")

    async def t():
        from foundationdb_tpu.core.sim import Endpoint
        await loop.delay(2.0)
        assert ss.version.get() == 50
        # kill the only TLog: the cursor is now spinning in its internal
        # retry loop (timeout + rotate) with no live replica to reach
        net.kill("tlog:0")
        await loop.delay(5.0)  # definitely mid-retry now
        # recovery installs a successor epoch on a NEW tlog process
        tlog2 = net.new_process("tlog:1")
        msgs2 = [(v, [_set(b"k%03d" % v, b"v")]) for v in range(51, 71)]
        ScriptedTLog(tlog2, msgs2, end=71, kc=70)
        req = SetLogSystemRequest(
            epochs=[LogEpoch(begin=0, end=50, addrs=["tlog:0"]),
                    LogEpoch(begin=50, end=None, addrs=["tlog:1"])],
            rollback_to=50, recovery_count=ss.recovery_count + 1)
        await net.request(client,
                          Endpoint("ss:0", Token.STORAGE_SET_LOGSYSTEM), req)
        # without the mid-retry refresh the cursor spins on tlog:0 forever;
        # with it, ingestion resumes from the new epoch
        for _ in range(200):
            if ss.version.get() >= 70:
                break
            await loop.delay(0.5)
        assert ss.version.get() == 70, ss.version.get()
        assert ss.data.get(b"k070", 70) == b"v"

    loop.run_future(loop.spawn(t()), max_time=600.0)
