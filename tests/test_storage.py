"""Unit tests for VersionedMap, atomic ops, and the WriteMap overlay.

Reference test analogues: -r versionedmaptest (VersionedMap.h), the AtomicOps
workload (fdbserver/workloads/AtomicOps.actor.cpp), and the WriteDuringRead /
RyowCorrectness workloads for the overlay.
"""

import pytest

from foundationdb_tpu.client.writemap import WriteMap
from foundationdb_tpu.server.versioned_map import VersionedMap
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.types import (
    Mutation, MutationType, apply_atomic_op, make_versionstamp,
    substitute_versionstamp)


def S(k, v):
    return Mutation(MutationType.SET_VALUE, k, v)


def C(b, e):
    return Mutation(MutationType.CLEAR_RANGE, b, e)


class TestVersionedMap:
    def test_versioned_reads(self):
        m = VersionedMap()
        m.apply(10, S(b"a", b"1"))
        m.apply(20, S(b"a", b"2"))
        m.apply(30, C(b"a", b"b"))
        assert m.get(b"a", 5) is None
        assert m.get(b"a", 10) == b"1"
        assert m.get(b"a", 19) == b"1"
        assert m.get(b"a", 20) == b"2"
        assert m.get(b"a", 29) == b"2"
        assert m.get(b"a", 30) is None

    def test_clear_range_only_hides_from_clear_version(self):
        m = VersionedMap()
        for i, k in enumerate([b"a", b"b", b"c"]):
            m.apply(10 + i, S(k, k.upper()))
        m.apply(50, C(b"a", b"c"))
        data, _ = m.range_read(b"", b"z", 49)
        assert [k for k, _v in data] == [b"a", b"b", b"c"]
        data, _ = m.range_read(b"", b"z", 50)
        assert [k for k, _v in data] == [b"c"]

    def test_key_set_after_clear_reappears(self):
        m = VersionedMap()
        m.apply(10, S(b"a", b"1"))
        m.apply(20, C(b"a", b"b"))
        m.apply(30, S(b"a", b"3"))
        assert m.get(b"a", 20) is None
        assert m.get(b"a", 30) == b"3"

    def test_range_limits_and_more_flag(self):
        m = VersionedMap()
        for i in range(10):
            m.apply(10 + i, S(b"k%d" % i, b"v"))
        data, more = m.range_read(b"", b"z", 100, limit=3)
        assert len(data) == 3 and more
        data, more = m.range_read(b"", b"z", 100, limit=10)
        assert len(data) == 10 and not more
        data, more = m.range_read(b"", b"z", 100, reverse=True, limit=2)
        assert [k for k, _ in data] == [b"k9", b"k8"] and more

    def test_forget_before_gc(self):
        m = VersionedMap()
        m.apply(10, S(b"a", b"1"))
        m.apply(20, S(b"a", b"2"))
        m.apply(30, C(b"a", b"b"))
        m.apply(40, S(b"b", b"x"))
        m.forget_before(25)
        with pytest.raises(FDBError):
            m.get(b"a", 24)
        assert m.get(b"a", 25) == b"2"
        assert m.get(b"a", 35) is None
        # fully-dead tombstoned keys are dropped once outside the window
        m.forget_before(35)
        assert m.get(b"a", 40) is None
        assert m.key_count() == 1  # only b"b" remains

    def test_atomic_in_map(self):
        m = VersionedMap()
        m.apply(10, Mutation(MutationType.ADD_VALUE, b"n", (3).to_bytes(4, "little")))
        m.apply(20, Mutation(MutationType.ADD_VALUE, b"n", (4).to_bytes(4, "little")))
        assert int.from_bytes(m.get(b"n", 20), "little") == 7
        assert int.from_bytes(m.get(b"n", 10), "little") == 3


class TestAtomicOps:
    def test_add_wraps_and_pads(self):
        assert apply_atomic_op(MutationType.ADD_VALUE, None, (5).to_bytes(4, "little")) \
            == (5).to_bytes(4, "little")
        assert apply_atomic_op(MutationType.ADD_VALUE, (0xFFFFFFFF).to_bytes(4, "little"),
                               (1).to_bytes(4, "little")) == (0).to_bytes(4, "little")
        # width follows the operand
        assert apply_atomic_op(MutationType.ADD_VALUE, b"\x01\x00\x00\x00\x00\x00\x00\x00",
                               b"\x01\x00") == b"\x02\x00"

    def test_bitwise(self):
        assert apply_atomic_op(MutationType.AND, b"\x0f\xf0", b"\xff\x10") == b"\x0f\x10"
        assert apply_atomic_op(MutationType.AND, None, b"\xff\xff") == b"\x00\x00"
        assert apply_atomic_op(MutationType.OR, b"\x01", b"\x10") == b"\x11"
        assert apply_atomic_op(MutationType.XOR, b"\xff", b"\x0f") == b"\xf0"

    def test_min_max(self):
        five, nine = (5).to_bytes(4, "little"), (9).to_bytes(4, "little")
        assert apply_atomic_op(MutationType.MAX, five, nine) == nine
        assert apply_atomic_op(MutationType.MAX, nine, five) == nine
        assert apply_atomic_op(MutationType.MIN, nine, five) == five
        assert apply_atomic_op(MutationType.MIN, None, five) == five  # v2
        assert apply_atomic_op(MutationType.BYTE_MIN, b"abc", b"abd") == b"abc"
        assert apply_atomic_op(MutationType.BYTE_MAX, b"abc", b"b") == b"b"

    def test_append_if_fits(self):
        assert apply_atomic_op(MutationType.APPEND_IF_FITS, b"ab", b"cd") == b"abcd"
        big = b"x" * 99_999
        assert apply_atomic_op(MutationType.APPEND_IF_FITS, big, b"yy") == big

    def test_versionstamp(self):
        stamp = make_versionstamp(0x1122334455667788, 3)
        assert len(stamp) == 10
        param = b"AA" + b"\x00" * 10 + b"BB" + (2).to_bytes(4, "little")
        out = substitute_versionstamp(param, stamp)
        assert out == b"AA" + stamp + b"BB"


class TestWriteMap:
    def test_set_clear_interleave(self):
        w = WriteMap()
        w.set(b"a", b"1")
        w.clear_range(b"a", b"c")
        has, p, cleared = w.lookup(b"a")
        assert has and p.known and p.value is None
        assert w.is_cleared(b"b")
        w.set(b"b", b"2")
        has, p, _ = w.lookup(b"b")
        assert p.value == b"2"

    def test_write_conflict_ranges_coalesce(self):
        w = WriteMap()
        w.set(b"a", b"1")
        w.set(b"a\x00", b"2")
        w.clear_range(b"m", b"p")
        w.set(b"n", b"3")  # inside the clear
        ranges = w.write_conflict_ranges()
        assert (b"a", b"a\x00\x00") in ranges
        assert (b"m", b"p") in ranges
        assert len(ranges) == 2

    def test_pending_atomic_resolution(self):
        w = WriteMap()
        w.atomic_op(MutationType.ADD_VALUE, b"n", (2).to_bytes(4, "little"))
        w.atomic_op(MutationType.ADD_VALUE, b"n", (3).to_bytes(4, "little"))
        _, p, _ = w.lookup(b"n")
        assert not p.known
        assert int.from_bytes(p.resolve((10).to_bytes(4, "little")), "little") == 15
        # after a set, ops fold eagerly
        w.set(b"n", (1).to_bytes(4, "little"))
        w.atomic_op(MutationType.ADD_VALUE, b"n", (1).to_bytes(4, "little"))
        _, p, _ = w.lookup(b"n")
        assert p.known
        assert int.from_bytes(p.value, "little") == 2
