"""Cross-engine parity fuzz: memory, ssd, and redwood are three
implementations of ONE IKeyValueStore contract — the same mutation stream
must produce identical reads through commit and reopen cycles, whatever
each engine does internally (WAL snapshots, sqlite B-tree, LSM flushes and
compactions). Style of tests/test_vstore_parity.py, at the engine layer."""

import pytest

from foundationdb_tpu.core.sim import SimFile
from foundationdb_tpu.storage.kvstore import (
    MemoryKeyValueStore, SSDKeyValueStore)
from foundationdb_tpu.storage.redwood import RedwoodKeyValueStore
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


class _Trio:
    """The three engines side by side over one mutation surface."""

    def __init__(self, tmp_path, seed):
        self.rng = DeterministicRandom(seed)
        self.sim_files: dict[str, SimFile] = {}
        self.ssd_path = str(tmp_path / "parity.sqlite")
        self.memory = MemoryKeyValueStore(self._file("mem.0"),
                                          self._file("mem.1"))
        self.ssd = SSDKeyValueStore(self.ssd_path)
        self.redwood = self._open_redwood()

    def _file(self, name):
        if name not in self.sim_files:
            self.sim_files[name] = SimFile(name, self.rng.fork())
        return self.sim_files[name]

    def _open_redwood(self):
        return RedwoodKeyValueStore(
            self._file("rw.wal.0"), self._file("rw.wal.1"),
            self._file,
            lambda: [n for n in self.sim_files if n.startswith("rw.")
                     and not n.startswith("rw.wal")])

    def all(self):
        return [("memory", self.memory), ("ssd", self.ssd),
                ("redwood", self.redwood)]

    def reopen(self):
        """Clean shutdown + recovery on every engine (everything is
        committed by the caller first)."""
        self.memory = MemoryKeyValueStore(self._file("mem.0"),
                                          self._file("mem.1"))
        self.memory.recover()
        self.ssd.db.close()
        self.ssd = SSDKeyValueStore(self.ssd_path)
        self.redwood = self._open_redwood()
        self.redwood.recover()


def _check_parity(trio, rng):
    ref = trio.memory.get_range(b"", b"\xff" * 8)
    for name, eng in trio.all():
        assert eng.get_range(b"", b"\xff" * 8) == ref, name
        assert eng.get_range(b"", b"\xff" * 8, reverse=True) == \
            ref[::-1], name
        assert eng.get_range(b"", b"\xff" * 8, limit=7) == ref[:7], name
        assert eng.get_range(b"", b"\xff" * 8, limit=0) == [], name
    # random sub-ranges + point reads
    for _ in range(5):
        a = f"k{rng.randint(0, 150):04d}".encode()
        b = f"k{rng.randint(0, 150):04d}".encode()
        begin, end = min(a, b), max(a, b)
        sub = trio.memory.get_range(begin, end)
        pt = trio.memory.get(a)
        for name, eng in trio.all():
            assert eng.get_range(begin, end) == sub, name
            assert eng.get(a) == pt, name
    meta = trio.memory.get_metadata("durableVersion")
    for name, eng in trio.all():
        assert eng.get_metadata("durableVersion") == meta, name


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_three_engines_same_stream_same_reads(tmp_path, seed):
    KNOBS.set("REDWOOD_MEMTABLE_BYTES", 512)
    KNOBS.set("REDWOOD_BLOCK_BYTES", 128)
    KNOBS.set("REDWOOD_COMPACTION_FAN_IN", 2)
    trio = _Trio(tmp_path, seed)
    rng = DeterministicRandom(seed * 7 + 1)
    trio.memory.SNAPSHOT_OPS = 50  # exercise WAL snapshotting too
    for step in range(500):
        r = rng.random()
        if r < 0.65:
            k = f"k{rng.randint(0, 150):04d}".encode()
            v = bytes(rng.randint(0, 255)
                      for _ in range(rng.randint(1, 12)))
            for _n, eng in trio.all():
                eng.set(k, v)
        elif r < 0.80:
            a = f"k{rng.randint(0, 150):04d}".encode()
            b = f"k{rng.randint(0, 150):04d}".encode()
            begin, end = min(a, b), max(a, b)
            for _n, eng in trio.all():
                eng.clear_range(begin, end)
        elif r < 0.90:
            for _n, eng in trio.all():
                eng.set_metadata("durableVersion", str(step).encode())
        else:
            for _n, eng in trio.all():
                eng.commit()
            trio.redwood.maintain()  # flush/compact between commits
            _check_parity(trio, rng)
            if rng.random() < 0.3:
                trio.reopen()
                _check_parity(trio, rng)
    for _n, eng in trio.all():
        eng.commit()
    trio.reopen()
    _check_parity(trio, rng)
    # the redwood instance must have actually exercised its LSM path
    assert trio.redwood.run_names(), "no runs flushed — budgets too large?"
