"""Unit tests for the utility layer (keys, errors, knobs, rng)."""

import numpy as np
import pytest

from foundationdb_tpu.utils import keys as K
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


def test_key_encoding_roundtrip():
    for k in [b"", b"a", b"abc", b"\x00", b"\xff" * 10, b"x" * 24]:
        assert K.decode_key(K.encode_key(k)) == k


def test_key_encoding_order_matches_bytes_order():
    rng = DeterministicRandom(1)
    ks = [rng.random_bytes(rng.randint(0, 24)) for _ in range(300)]
    ks += [b"abc", b"abc\x00", b"abd", b"ab", b"", b"\xff" * 24]
    enc = [K.encode_key(k) for k in ks]
    for i in range(len(ks)):
        for j in range(i + 1, len(ks)):
            want = (ks[i] > ks[j]) - (ks[i] < ks[j])
            got = K.compare_encoded(enc[i], enc[j])
            assert got == want, (ks[i], ks[j])


def test_key_truncation_is_prefix_collapse():
    long1 = b"p" * 24 + b"a"
    long2 = b"p" * 24 + b"b"
    assert K.compare_encoded(K.encode_key(long1), K.encode_key(long2)) == 0
    assert K.compare_encoded(K.encode_key(b"p" * 24), K.encode_key(long1)) == 0


def test_max_sentinel_greater_than_all():
    for k in [b"", b"\xff" * 24, b"\xff" * 100]:
        assert K.compare_encoded(K.encode_key(k), K.MAX_LIMBS) == -1


def test_encode_keys_batch():
    ks = [b"a", b"bb", b"ccc"]
    arr = K.encode_keys(ks)
    assert arr.shape == (K.NUM_LIMBS, 3)
    for i, k in enumerate(ks):
        assert K.decode_key(arr[:, i]) == k


def test_strinc_and_key_after():
    assert K.strinc(b"a") == b"b"
    assert K.strinc(b"a\xff\xff") == b"b"
    assert K.key_after(b"a") == b"a\x00"
    with pytest.raises(ValueError):
        K.strinc(b"\xff")


def test_errors():
    e = FDBError("not_committed")
    assert e.code == 1020 and e.is_retryable
    e2 = FDBError("io_error")
    assert not e2.is_retryable
    with pytest.raises(ValueError):
        FDBError("no_such_error")


def test_knobs_buggify_deterministic():
    r1, r2 = DeterministicRandom(7), DeterministicRandom(7)
    KNOBS.buggify(r1)
    snap1 = dict(KNOBS._values)
    KNOBS.reset()
    KNOBS.buggify(r2)
    assert dict(KNOBS._values) == snap1


def test_rng_determinism():
    a, b = DeterministicRandom(42), DeterministicRandom(42)
    assert [a.randint(0, 100) for _ in range(10)] == [b.randint(0, 100) for _ in range(10)]
    assert a.fork().random() == b.fork().random()


class TestIndexedSet:
    """flow/IndexedSet.h parity: the C skiplist and the Python fallback make
    identical decisions (insert/discard/rank/nth/ranges/sums), and the
    augmented sums answer range metrics in O(log n)."""

    def _pair(self):
        from foundationdb_tpu.utils.indexedset import (
            PyIndexedSet, make_indexed_set)
        return make_indexed_set(), PyIndexedSet()

    def test_fuzz_parity_with_python_fallback(self):
        import random
        s, p = self._pair()
        rng = random.Random(99)
        for _ in range(3000):
            op = rng.random()
            k = b"k%05d" % rng.randrange(900)
            if op < 0.55:
                m = rng.randrange(1, 50)
                s.insert(k, m)
                p.insert(k, m)
            elif op < 0.75:
                assert s.discard(k) == p.discard(k)
            else:
                lo = b"k%05d" % rng.randrange(900)
                hi = b"k%05d" % rng.randrange(900)
                if lo > hi:
                    lo, hi = hi, lo
                assert s.rank(lo) == p.rank(lo)
                assert tuple(s.sum_range(lo, hi)) == tuple(p.sum_range(lo, hi))
                assert s.range_keys(lo, hi, 7, False) == \
                    p.range_keys(lo, hi, 7, False)
                assert s.range_keys(lo, hi, 7, True) == \
                    p.range_keys(lo, hi, 7, True)
        assert len(s) == len(p)
        for i in (0, len(p) // 3, len(p) - 1):
            if 0 <= i < len(p):
                assert s.nth(i) == p.nth(i)

    def test_metric_replace_updates_sums(self):
        s, _ = self._pair()
        s.insert(b"a", 10)
        s.insert(b"b", 20)
        s.insert(b"c", 30)
        assert tuple(s.sum_range(b"a", b"d")) == (3, 60)
        s.insert(b"b", 5)  # re-metric
        assert tuple(s.sum_range(b"a", b"d")) == (3, 45)
        assert tuple(s.sum_range(b"b", b"c")) == (1, 5)

    def test_lazy_iteration_matches_range(self):
        from foundationdb_tpu.utils.indexedset import iter_range
        s, _ = self._pair()
        for i in range(500):
            s.insert(b"%05d" % i, 1)
        assert list(iter_range(s, b"00100", b"00400", chunk=13)) == \
            [b"%05d" % i for i in range(100, 400)]
        assert list(iter_range(s, b"00100", b"00400", reverse=True,
                               chunk=7)) == \
            [b"%05d" % i for i in range(399, 99, -1)]
