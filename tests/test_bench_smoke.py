"""Smoke test for the e2e bench driver: a ~2-second slice of every phase on
both topologies (merged and proxy fan-out) must complete and emit a
well-formed report. Guards the measurement harness itself — a broken
bench_e2e.py otherwise goes unnoticed until a round's official run.

Numbers from these slices are meaningless (tiny load, shared CI core); only
shape, completion, and the gross scale-out invariant are asserted.
"""

import json

import pytest

import bench_e2e

_PHASES = ("write", "read", "mixed")


@pytest.fixture(scope="module")
def reports():
    """One run per topology, shared by every assertion in this module —
    booting the process cluster twice is the whole cost of this file."""
    out = {}
    for n_proxies in (0, 2):
        out[n_proxies] = bench_e2e.run(
            clients=40, seconds=0.5, backend="oracle", n_proxies=n_proxies,
            n_storage=1, n_client_procs=1)
    return out


def _check_report(report: dict, n_proxies: int):
    # JSON round-trip: the official run is consumed as BENCH_rNN.json
    decoded = json.loads(json.dumps(report))
    # topology records what was RECRUITED: the merged layout co-locates ONE
    # commit proxy in the core process (the r09 rows said "proxies": 0)
    assert decoded["topology"] == {
        "commit_proxies": max(n_proxies, 1), "grv_proxies": 0,
        "storage": 1, "replicas": 1, "client_procs": 1,
        "merged_core": n_proxies == 0}
    assert decoded["conflict_backend"] == "oracle"
    for kind in _PHASES:
        entry = decoded[kind]
        assert entry["ops_per_sec"] > 0, (kind, entry)
        assert entry["vs_baseline"] > 0
        assert entry["ops_per_sec"] / bench_e2e.BASELINES[kind] == \
            pytest.approx(entry["vs_baseline"], abs=1e-3)
        # every phase awaits GRV; write and mixed phases commit
        assert "grv_ms_p50" in entry
        if kind != "read":
            assert "commit_ms_p50" in entry and "commit_ms_p99" in entry


@pytest.mark.parametrize("n_proxies", [0, 2], ids=["merged", "fanout2"])
def test_bench_slice(reports, n_proxies):
    _check_report(reports[n_proxies], n_proxies)


def test_scale_out_not_collapsed(reports):
    """The scale-out invariant on the smoke slice: adding a second proxy
    process must not collapse write throughput (BENCH_r08 measured 0.53x).
    The official >= 1.0x gate runs on the standing BENCH_rNN rows at full
    load; this CI slice is tiny and shares one core across every process,
    so it only guards against gross regressions — hence the 0.75 slack."""
    merged = reports[0]["write"]["ops_per_sec"]
    fanout = reports[2]["write"]["ops_per_sec"]
    assert fanout >= 0.75 * merged, (fanout, merged)


def test_grv_split_slice():
    """A dedicated-GRV-proxy topology boots, serves all phases, and records
    the split in the topology metadata."""
    report = bench_e2e.run(clients=20, seconds=0.5, backend="oracle",
                           n_proxies=1, n_grv_proxies=1, n_storage=1,
                           n_client_procs=1, phases=("mixed",))
    decoded = json.loads(json.dumps(report))
    assert decoded["topology"]["commit_proxies"] == 1
    assert decoded["topology"]["grv_proxies"] == 1
    assert decoded["mixed"]["ops_per_sec"] > 0
    assert "grv_ms_p50" in decoded["mixed"]


def test_redwood_read_slice():
    """Tier-1 smoke for the redwood native read path end-to-end: a short
    write+read slice on a cluster whose storage engine is redwood with a
    memtable small enough that the preload flushes real runs (so recovery
    and serving open C run handles where the extension is available; the
    pure-Python fallback serves the same slice elsewhere). Guards boot,
    WAL/flush/compaction under the bench driver, and the read phase over a
    flushed engine — not performance."""
    report = bench_e2e.run(
        clients=20, seconds=0.5, backend="oracle", n_proxies=0,
        n_storage=1, n_client_procs=1, phases=("write", "read"),
        extra_knobs={"STORAGE_ENGINE": "redwood",
                     "REDWOOD_MEMTABLE_BYTES": 16384})
    decoded = json.loads(json.dumps(report))
    assert decoded["write"]["ops_per_sec"] > 0
    assert decoded["read"]["ops_per_sec"] > 0
    assert "grv_ms_p50" in decoded["read"]


def test_replicated_read_slice():
    """Tier-1 smoke for the read scale-out topology: one shard, two
    storage replicas, both recruited into the client's location cache as
    one team. Guards the replicated boot path (per-replica tags fed by the
    same log), the hedged/EWMA multi-replica read path, and the ledger
    plumbing — both replicas must actually serve, with zero errors."""
    report = bench_e2e.run(clients=20, seconds=0.5, backend="oracle",
                           n_proxies=0, n_storage=1, n_replicas=2,
                           n_client_procs=1, phases=("read",))
    decoded = json.loads(json.dumps(report))
    assert decoded["topology"]["replicas"] == 2
    entry = decoded["read"]
    assert entry["ops_per_sec"] > 0
    assert entry["errors"] == {}
    served = entry["storage_reads_by_proc"]
    assert len(served) == 2 and all(v > 0 for v in served.values()), served
    assert entry["watermark_rejects"] == 0  # static shards: no fencing


def test_zipfian_cache_slice():
    """Tier-1 smoke for the versioned hot-key read cache under the bench
    driver: the zipfian-read phase must complete cleanly and the storage
    cache ledger must show hits on the hot prefix (the 1.5s untimed ramp
    spans the sketch's 0.5s hot-set refresh, so the cache is warm inside
    the measured window)."""
    report = bench_e2e.run(clients=20, seconds=1.0, backend="oracle",
                           n_proxies=0, n_storage=1,
                           n_client_procs=1, phases=("zipfian-read",))
    entry = json.loads(json.dumps(report))["zipfian-read"]
    assert entry["ops_per_sec"] > 0
    assert entry["errors"] == {}
    assert entry["read_cache"]["hits"] > 0, entry["read_cache"]


def test_native_client_read_slice(monkeypatch):
    """Tier-1 smoke for the native client plane end-to-end under the bench
    driver: a short read slice with NET_NATIVE_CLIENT=1 (batched C request
    encode + ClientConn reply pump on every client connection) must boot,
    serve multigets, and return the same values as the ablation run with
    the plane off — the parity contract BENCH_r15's rows rest on. Guards
    wiring, not performance."""
    from foundationdb_tpu.net import native_transport as nt
    if not nt.client_available():
        pytest.skip("C extension lacks the client plane")
    reports = {}
    for on in ("1", "0"):
        monkeypatch.setenv("NET_NATIVE_TRANSPORT", "1")
        monkeypatch.setenv("NET_NATIVE_CLIENT", on)
        reports[on] = bench_e2e.run(
            clients=20, seconds=0.5, backend="oracle", n_proxies=0,
            n_storage=1, n_client_procs=1, phases=("read",))
    for on, report in reports.items():
        decoded = json.loads(json.dumps(report))
        assert decoded["read"]["ops_per_sec"] > 0, on
        assert "grv_ms_p50" in decoded["read"]
        # parity: the native plane must not trade correctness for speed —
        # a decode bug shows up here as per-txn read errors
        assert decoded["read"].get("errors", {}) == {}, on


def test_sharded_backend_slice(monkeypatch):
    """Tier-1 smoke for the SHARDED conflict backend: a short commit burst
    through a real process cluster whose resolver runs the 2-wide SPMD mesh
    on forced host-platform CPU devices. Guards the whole sharded serving
    path — knob validation, mesh boot, shard_map dispatch, verdict readback
    — not its performance (CPU devices share one core)."""
    monkeypatch.setenv("FDBTPU_E2E_FORCE_CPU", "1")
    monkeypatch.setenv("FDBTPU_E2E_CPU_JAX", "1")
    monkeypatch.setenv("FDBTPU_E2E_HOST_DEVICES", "2")
    report = bench_e2e.run(
        clients=20, seconds=0.5, backend="sharded", n_proxies=0,
        n_storage=1, n_client_procs=1, phases=("write",),
        extra_knobs={
            # small enough to compile fast on the host XLA backend, big
            # enough that the preload's 100-write txns fit one batch
            # (16 txns x 8 writes = 128 write slots) and that the state
            # table holds the whole run's boundaries (the preload alone
            # writes 2000 distinct keys = ~4000 boundaries; overflowing
            # the table rightly POISONS the resolver)
            "CONFLICT_NUM_SHARDS": 2,
            "CONFLICT_BATCH_TXNS": 16,
            "CONFLICT_BATCH_READS_PER_TXN": 2,
            "CONFLICT_BATCH_WRITES_PER_TXN": 8,
            "CONFLICT_STATE_CAPACITY": 32768,
        })
    decoded = json.loads(json.dumps(report))
    assert decoded["conflict_backend"] == "sharded"
    assert decoded["accelerator"] == "cpu-fallback"
    assert decoded["detect_evaluator"] == "jax-cpu"
    assert decoded["write"]["ops_per_sec"] > 0
    assert "commit_ms_p50" in decoded["write"]
