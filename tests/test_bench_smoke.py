"""Smoke test for the e2e bench driver: a ~2-second slice of every phase on
both topologies (merged and proxy fan-out) must complete and emit a
well-formed report. Guards the measurement harness itself — a broken
bench_e2e.py otherwise goes unnoticed until a round's official run.

Numbers from these slices are meaningless (tiny load, shared CI core); only
shape and completion are asserted.
"""

import json

import pytest

import bench_e2e

_PHASES = ("write", "read", "mixed")


def _check_report(report: dict, n_proxies: int):
    # JSON round-trip: the official run is consumed as BENCH_rNN.json
    decoded = json.loads(json.dumps(report))
    assert decoded["topology"] == {"proxies": n_proxies, "storage": 1,
                                   "client_procs": 1}
    assert decoded["conflict_backend"] == "oracle"
    for kind in _PHASES:
        entry = decoded[kind]
        assert entry["ops_per_sec"] > 0, (kind, entry)
        assert entry["vs_baseline"] > 0
        assert entry["ops_per_sec"] / bench_e2e.BASELINES[kind] == \
            pytest.approx(entry["vs_baseline"], abs=1e-3)
        # every phase awaits GRV; write and mixed phases commit
        assert "grv_ms_p50" in entry
        if kind != "read":
            assert "commit_ms_p50" in entry and "commit_ms_p99" in entry


@pytest.mark.parametrize("n_proxies", [0, 2], ids=["merged", "fanout2"])
def test_bench_slice(n_proxies):
    report = bench_e2e.run(clients=40, seconds=0.5, backend="oracle",
                           n_proxies=n_proxies, n_storage=1,
                           n_client_procs=1)
    _check_report(report, n_proxies)
