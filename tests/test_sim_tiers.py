"""Graded randomized-simulation tiers (testing/simulated_cluster).

Fast tier: a bounded seeded sweep — every seed draws its own cluster
(topology, replication mode, storage engine, conflict backend, buggified
knobs) exactly like SimulatedCluster.actor.cpp:1239, then runs one randomly
picked fast spec against it. Plus one pinned (seed, spec) pair per fast spec
so every workload in the battery provably runs alongside a fault workload in
tier-1, whatever the sweep happens to draw.

Slow tier (pytest -m slow): the long compositions — backup under attrition,
the swizzled battery, two-region fuzz.

Every failure surfaces a one-line repro command in the pytest report via
SpecFailure's message (run_randomized_spec prints it too).
"""

import pytest

from foundationdb_tpu.testing import simulated_cluster as SC

# Pinned sweep seeds: verified to pass AND to draw pairwise-distinct
# (topology, replication, engine, backend, knobs) tuples covering single /
# double / two-region replication, all three engines, and all three default
# backends. If a code change makes one fail, the printed repro line replays
# it. (Re-picked when DEFAULT_BACKENDS grew sharded: widening an allow-list
# shifts every downstream randint for every seed.)
FAST_SWEEP_SEEDS = [1, 2, 3, 4, 5, 7, 8, 10, 13, 15, 19, 25, 38, 46]

# One pinned pair per fast spec (seed drawn compatible with the spec's
# needs): the guarantee that EVERY workload — fuzz battery and deepened
# ConflictRange included — exercises at least one spec with faults in
# tier-1. Mostly-oracle draws for cheapness; cycle deliberately pins a
# SHARDED draw so the SPMD mesh path runs under faults in tier-1 even if
# the sweep's wall-clock budget skips its sharded seeds.
PINNED_FAST = [
    ("cycle", 15),            # single/memory/sharded
    ("zipfian-hotkey", 2),    # single/memory/oracle (needs flat)
    ("zipfian-read-hotspot", 25),  # double/memory/oracle (needs flat):
    # the 2-replica draw, so the hedged multi-replica client path serves
    # the skewed readers through clogging + attrition

    ("conflict-range", 2),    # single/memory/oracle
    ("fuzz-api", 19),         # single/redwood/oracle
    ("serializability", 23),  # single/ssd/oracle
    ("ryow", 22),             # single/memory/oracle
    ("change-config", 33),    # double/redwood/oracle (needs flat)
    ("remove-servers", 36),   # double/memory/device + spare storage
    ("kill-region", 49),      # two_region/ssd/oracle
]

PINNED_SLOW = [
    ("backup-attrition", 24),  # single/redwood/oracle (needs flat)
    ("swizzled-battery", 25),  # double/memory/oracle
    ("two-region-fuzz", 43),   # two_region/redwood/oracle
]


def test_fast_sweep_draws_are_distinct_and_cover_the_axes():
    """Pure draw check (no clusters booted): the sweep seeds below must
    draw pairwise-distinct environment tuples and between them cover every
    replication mode, all three storage engines, and all three default
    backends."""
    draws = [SC.ClusterDraw.draw(s) for s in FAST_SWEEP_SEEDS]
    tuples = {d.distinct_tuple() for d in draws}
    assert len(tuples) == len(draws), "sweep seeds drew duplicate clusters"
    assert len(draws) >= 12
    assert {d.replication for d in draws} == \
        {"single", "double", "two_region"}
    assert {d.storage_engine for d in draws} == {"memory", "ssd", "redwood"}
    assert {d.conflict_backend for d in draws} == \
        {"oracle", "device", "sharded"}


def test_fast_tier_sweep():
    """The CI sweep: run the fast tier over the pinned seeds under a wall
    clock cap. At least 12 seeds must complete (a too-slow environment
    fails loudly instead of eating the whole tier-1 budget), and the draws
    that ran must be pairwise distinct — asserted on the RESULTS, not just
    the seed list."""
    results = SC.sweep(FAST_SWEEP_SEEDS, tier="fast",
                       wall_clock_budget=420.0)
    assert len(results) >= 12, \
        f"only {len(results)} sweep seeds finished inside the budget"
    tuples = {r.draw.distinct_tuple() for r in results}
    assert len(tuples) == len(results)


@pytest.mark.parametrize("spec_name,seed", PINNED_FAST,
                         ids=[s for s, _ in PINNED_FAST])
def test_fast_spec(spec_name, seed):
    r = SC.run_randomized_spec(seed, spec=spec_name)
    assert r.spec == spec_name
    assert r.result.elapsed > 0


@pytest.mark.slow
@pytest.mark.parametrize("spec_name,seed", PINNED_SLOW,
                         ids=[s for s, _ in PINNED_SLOW])
def test_slow_spec(spec_name, seed):
    r = SC.run_randomized_spec(seed, spec=spec_name)
    assert r.spec == spec_name


def test_spec_failure_carries_the_repro_line():
    """Any failing spec must surface the one-line repro command in the
    exception pytest reports (and print it): inject a spec whose check
    always fails and assert the repro format."""
    from foundationdb_tpu.testing.workloads import Workload

    class AlwaysFails(Workload):
        name = "AlwaysFails"

        async def check(self, db):
            raise AssertionError("injected failure")

    spec = SC.Spec("always-fails", "fast", lambda rng: [AlwaysFails()],
                   duration=2.0)
    with pytest.raises(SC.SpecFailure) as ei:
        SC.run_randomized_spec(2, spec=spec,
                               allow_backends=("oracle",))
    msg = str(ei.value)
    assert "--seed 2 --spec always-fails" in msg
    assert "python -m foundationdb_tpu.testing.simulated_cluster" in msg
    assert "drew:" in msg


def test_incompatible_explicit_spec_is_rejected():
    """Asking for a two-region spec on a seed that drew a flat cluster is a
    usage error, not a silent re-draw (the repro line must stay honest)."""
    d = SC.ClusterDraw.draw(2)
    assert d.replication != "two_region"
    with pytest.raises(ValueError):
        SC.run_randomized_spec(2, spec="kill-region")
