"""Durability layer: DiskQueue, KV engines, storage/TLog restart recovery.

Reference test strategy (SURVEY.md §4): kill/reboot with non-durable files
(AsyncFileNonDurable) proves fsync semantics; restart specs
(tests/restarting/) prove resume. Here: the DiskQueue survives synced pushes
and loses only a torn tail; the memory engine recovers snapshot+WAL; a
rebooted storage server serves all previously committed data even after the
TLog was popped below it.
"""

import pytest

from foundationdb_tpu.core.sim import KillType, SimFile
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.storage.kvstore import MemoryKeyValueStore
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


def _files(n=2, seed=0):
    rng = DeterministicRandom(seed)
    return [SimFile(f"f{i}", rng.fork()) for i in range(n)]


# ---------------------------------------------------------------------------
# DiskQueue
# ---------------------------------------------------------------------------

def test_diskqueue_push_commit_recover():
    f0, f1 = _files()
    q = DiskQueue(f0, f1)
    for i in range(10):
        q.push(f"entry{i}".encode())
    q.commit()
    q2 = DiskQueue(f0, f1)
    entries = q2.recover()
    assert [p for _s, p in entries] == [f"entry{i}".encode() for i in range(10)]
    assert q2.next_seq == 10


def test_diskqueue_uncommitted_lost_on_kill():
    f0, f1 = _files(seed=3)
    q = DiskQueue(f0, f1)
    q.push(b"durable")
    q.commit()
    q.push(b"lost1")
    q.push(b"lost2")
    f0.on_kill()  # unsynced appends dropped (possibly a prefix survives)
    f1.on_kill()
    entries = DiskQueue(f0, f1).recover()
    payloads = [p for _s, p in entries]
    assert payloads[0] == b"durable"
    # suffix-only loss: if lost2 survived, lost1 must have too
    if b"lost2" in payloads:
        assert b"lost1" in payloads


def test_diskqueue_pop_truncates_and_alternates():
    f0, f1 = _files()
    q = DiskQueue(f0, f1)
    for i in range(100):
        q.push(bytes([i]))
    q.commit()
    q.pop(90)  # front file fully popped -> truncate + swap
    assert q.active == 1  # writes now land in the emptied file
    for i in range(100, 110):
        q.push(bytes([i % 256]))
    q.commit()
    entries = DiskQueue(f0, f1).recover()
    seqs = [s for s, _p in entries]
    assert seqs[0] >= 90 or len(seqs) == 20  # popped prefix gone from disk
    payloads = [p for _s, p in entries]
    assert bytes([109]) in payloads


def test_diskqueue_torn_page_truncates_suffix():
    f0, f1 = _files()
    q = DiskQueue(f0, f1)
    for i in range(5):
        q.push(bytes([i]) * 10)
    q.commit()
    # corrupt the middle of the raw file: recovery must stop there
    raw = f0.durable
    f0.durable = raw[: len(raw) // 2] + b"\xde\xad" + raw[len(raw) // 2 + 2:]
    entries = DiskQueue(f0, f1).recover()
    assert len(entries) < 5


# ---------------------------------------------------------------------------
# Memory KV engine
# ---------------------------------------------------------------------------

def test_memory_kvstore_recover():
    f0, f1 = _files()
    s = MemoryKeyValueStore(f0, f1)
    s.set(b"a", b"1")
    s.set(b"b", b"2")
    s.set(b"c", b"3")
    s.clear_range(b"b", b"c")
    s.set_metadata("durableVersion", b"42")
    s.commit()
    s2 = MemoryKeyValueStore(f0, f1)
    s2.recover()
    assert s2.get(b"a") == b"1"
    assert s2.get(b"b") is None
    assert s2.get(b"c") == b"3"
    assert s2.get_range(b"", b"\xff") == [(b"a", b"1"), (b"c", b"3")]
    assert s2.get_metadata("durableVersion") == b"42"


def test_memory_kvstore_snapshot_compaction():
    f0, f1 = _files()
    s = MemoryKeyValueStore(f0, f1)
    s.SNAPSHOT_OPS = 10
    for i in range(25):
        s.set(f"k{i}".encode(), f"v{i}".encode())
        s.commit()
    # snapshots happened; a fresh recover still sees everything
    s2 = MemoryKeyValueStore(f0, f1)
    s2.recover()
    for i in range(25):
        assert s2.get(f"k{i}".encode()) == f"v{i}".encode()
    # and the disk footprint was compacted (all entries fit post-snapshot)
    assert len(s.queue.live_entries) < 25


def test_ssd_kvstore(tmp_path):
    from foundationdb_tpu.storage.kvstore import SSDKeyValueStore
    s = SSDKeyValueStore(str(tmp_path / "kv.sqlite"))
    s.set(b"x", b"1")
    s.set(b"y", b"2")
    s.commit()
    s2 = SSDKeyValueStore(str(tmp_path / "kv.sqlite"))
    assert s2.get(b"x") == b"1"
    assert s2.get_range(b"", b"\xff") == [(b"x", b"1"), (b"y", b"2")]
    s2.clear_range(b"x", b"y")
    s2.commit()
    assert s2.get(b"x") is None


# ---------------------------------------------------------------------------
# Storage server restart recovery (whole-cluster, through the client API)
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


@pytest.mark.parametrize("engine", ["memory", "ssd", "redwood"])
def test_storage_server_reboot_preserves_durable_data(engine, tmp_path):
    # small MVCC window so durability advances quickly; the storage role
    # opens the configured engine via open_kv_store (IKeyValueStore.h:66)
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 50)
    KNOBS.set("MAX_VERSIONS_IN_FLIGHT", 1_000_000_000)
    KNOBS.set("STORAGE_ENGINE", engine)
    KNOBS.set("SSD_DATA_DIR", str(tmp_path))
    if engine == "redwood":
        # tiny budgets: the 30-key write set must cross a flush so the
        # reboot recovers run files + WAL, not just the WAL
        KNOBS.set("REDWOOD_MEMTABLE_BYTES", 256)
        KNOBS.set("REDWOOD_BLOCK_BYTES", 512)
        KNOBS.set("REDWOOD_COMPACTION_FAN_IN", 2)
    c = SimCluster(seed=5)
    db = c.database()
    ss_addr = c.storage_procs[0].address

    async def scenario():
        # phase 1: write data, push versions forward so it becomes durable
        for i in range(30):
            tr = db.create_transaction()
            tr.set(f"key{i:03d}".encode(), f"val{i}".encode())
            await tr.commit()
        await c.loop.delay(1.0)

        # phase 2: reboot the storage server (durable files survive,
        # unsynced tails may be lost)
        c.net.kill(ss_addr, KillType.RebootProcess)
        await c.loop.delay(5.0)

        # phase 3: all committed data must still be readable
        tr = db.create_transaction()
        for i in range(30):
            v = await tr.get(f"key{i:03d}".encode())
            assert v == f"val{i}".encode(), (i, v)

    c.run(c.loop.spawn(scenario()), max_time=300.0)


def test_tlog_reboot_preserves_unpopped_mutations():
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 50)
    KNOBS.set("MAX_VERSIONS_IN_FLIGHT", 1_000_000_000)
    c = SimCluster(seed=6)
    db = c.database()
    tlog_addr = c.tlog_procs[0].address

    async def scenario():
        tr = db.create_transaction()
        tr.set(b"before", b"1")
        await tr.commit()
        await c.loop.delay(0.5)

        c.net.kill(tlog_addr, KillType.RebootProcess)
        await c.loop.delay(5.0)

        # data committed before the crash still readable (either already
        # durable at the SS, or re-peeked from the recovered TLog)
        tr = db.create_transaction()
        assert await tr.get(b"before") == b"1"

        # and the pipeline still works end-to-end after recovery
        tr2 = db.create_transaction()
        tr2.set(b"after", b"2")
        await tr2.commit()
        tr3 = db.create_transaction()
        assert await tr3.get(b"after") == b"2"

    c.run(c.loop.spawn(scenario()), max_time=300.0)
