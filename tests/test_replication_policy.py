"""Replication policy engine (fdbrpc/ReplicationPolicy.h:99-127): validate +
select_replicas over locality attributes, and policy-aware team placement in
the cluster controller.
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.server.replication import (
    LocalityData, PolicyAcross, PolicyAnd, PolicyOne, policy_for_replication,
    select_replicas)
from foundationdb_tpu.utils.knobs import KNOBS


def L(z, dc="dc0", m=None):
    return LocalityData(process_id=f"{z}-{m or z}", zone_id=z,
                        machine_id=m or z, dc_id=dc)


def test_policy_validate():
    triple = PolicyAcross(3, "zoneid")
    assert triple.validate([L("z1"), L("z2"), L("z3")])
    assert not triple.validate([L("z1"), L("z1"), L("z2")])
    assert triple.validate([L("z1"), L("z1"), L("z2"), L("z3")])

    two_dc = PolicyAcross(2, "dcid", PolicyAcross(2, "zoneid"))
    assert two_dc.validate([L("z1", "dcA"), L("z2", "dcA"),
                            L("z3", "dcB"), L("z4", "dcB")])
    assert not two_dc.validate([L("z1", "dcA"), L("z2", "dcA"),
                                L("z3", "dcB"), L("z3", "dcB")])

    both = PolicyAnd((PolicyAcross(2, "zoneid"), PolicyAcross(2, "dcid")))
    assert both.validate([L("z1", "dcA"), L("z2", "dcB")])
    assert not both.validate([L("z1", "dcA"), L("z2", "dcA")])


def test_select_replicas_prefers_distinct_zones():
    cands = [("a", L("z1")), ("b", L("z1")), ("c", L("z2")), ("d", L("z3"))]
    picks = select_replicas(PolicyAcross(3, "zoneid"), cands)
    assert picks is not None
    zones = {dict(cands)[a].zone_id for a in picks}
    assert len(zones) == 3

    # impossible: only 2 zones available
    assert select_replicas(PolicyAcross(3, "zoneid"),
                           [("a", L("z1")), ("b", L("z1")),
                            ("c", L("z2"))]) is None


def test_select_replicas_with_already():
    cands = [("c", L("z1")), ("d", L("z2")), ("e", L("z3"))]
    picks = select_replicas(PolicyAcross(2, "zoneid"), cands,
                            already=[("a", L("z1"))])
    assert picks is not None and len(picks) == 1
    assert dict(cands)[picks[0]].zone_id != "z1"


def test_nested_policy_selection():
    # 2 DCs x 2 zones each
    cands = [("a", L("z1", "dcA")), ("b", L("z2", "dcA")),
             ("c", L("z1b", "dcA")),
             ("d", L("z3", "dcB")), ("e", L("z4", "dcB"))]
    pol = PolicyAcross(2, "dcid", PolicyAcross(2, "zoneid"))
    picks = select_replicas(pol, cands)
    assert picks is not None
    locs = [dict(cands)[a] for a in picks]
    assert pol.validate(locs), picks


def test_cluster_places_teams_across_zones():
    """Storage workers on 3 machines (2 workers each): every double-
    replicated team must span two MACHINES (zone = machine id here), and a
    heal after losing a worker keeps the property."""
    from foundationdb_tpu.core.sim import KillType
    from foundationdb_tpu.server.cluster import RecoverableCluster

    KNOBS.set("CONFLICT_BACKEND", "oracle")
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    KNOBS.set("DD_STORAGE_FAILURE_SECONDS", 4.0)
    c = RecoverableCluster(seed=92, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=2, n_replicas=2, n_storage_workers=6)
    # co-locate storage workers pairwise on 3 machines
    for i, p in enumerate(c.storage_worker_procs):
        p.machine_id = f"machine{i // 2}"
    db = c.database()

    def zone_of(cc, addr):
        return cc.registry.locality_of(addr).zone_id

    async def t():
        await db.refresh()
        cc = c.current_cc()
        # wait until localities registered and teams known
        for _ in range(30):
            await c.loop.delay(1.0)
            cc = c.current_cc()
            if cc and len(getattr(cc.registry, "localities", {})) >= 6:
                break
        info = cc.dbinfo
        addr_of = {t_: a for a, t_ in info.storages}
        for team in info.teams():
            zones = {zone_of(cc, addr_of[t_]) for t_ in team}
            assert len(zones) == 2, (team, zones)

        # lose one member; the heal should pick a replacement keeping the
        # team across two machines
        victim = addr_of[info.teams()[0][0]]
        c.net.kill(victim, KillType.KillProcess)
        for _ in range(120):
            await c.loop.delay(0.5)
            cc = c.current_cc()
            if cc is None:
                continue
            info = cc.dbinfo
            vt = {t_ for a, t_ in info.storages if a == victim}
            if not any(t_ in team for t_ in vt for team in info.teams()):
                break
        info = c.current_cc().dbinfo
        addr_of = {t_: a for a, t_ in info.storages}
        cc = c.current_cc()
        for team in info.teams():
            zones = {zone_of(cc, addr_of[t_]) for t_ in team}
            assert len(zones) == 2, (team, zones)

    c.run(c.loop.spawn(t()), max_time=240_000.0)
    KNOBS.reset()
