"""Native client plane: batched C request encoder + ClientConn reply pump
vs the pure-Python client paths.

Three-way parity contract (ISSUE PR 19): (1) batch encode —
`native_transport.py_encode_batch` (pure Python), the C
`transport_client_encode`, and the concatenation of per-request
`py_frame(token, reply_id, _REQUEST, wire.dumps(payload))` bytes are all
identical, so a server cannot tell which encoder a client ran; (2) reply
pump — `ClientConn.feed` splits any byte stream (torn, corrupted,
oversized, undecodable, mixed-kind) into exactly the entries a reference
Python pump predicts, with identical reject decisions, identical residue,
and raw-bytes fallback wherever the C decoder declines (so Python's
wire.loads stays the semantic authority); (3) settlement — the transport's
_settle_batch resolves futures, cancels RPC timers, and degrades
mid-stream identically to the pure-Python reply loop.

The fuzz bodies (fuzz_*) are imported by scripts/native_sanitize_fuzz.py
stage 6 and re-run under ASan/UBSan — keep this module outside the jax
import closure (no transport.py/knobs/client imports at module scope).
"""

import random
import struct

import pytest

from foundationdb_tpu import native
from foundationdb_tpu.net import native_transport as nt
from foundationdb_tpu.server import interfaces as si
from foundationdb_tpu.utils import wire

HAVE_NATIVE = nt.client_available()
pytestmark = pytest.mark.skipif(
    not HAVE_NATIVE, reason="C extension lacks the client plane")

_REQUEST, _REPLY, _REPLY_ERROR, _ONE_WAY = 0, 1, 2, 3


# -- (1) batch encode parity --------------------------------------------------

def _rand_value(rng, depth=0):
    shape = rng.randrange(9 if depth < 2 else 7)
    if shape == 0:
        return None
    if shape == 1:
        return rng.random() < 0.5
    if shape == 2:  # stay within the 64-bit zigzag both codecs share
        return rng.randrange(-(1 << 60), 1 << 60)
    if shape == 3:
        return rng.uniform(-1e9, 1e9)
    if shape == 4:
        return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 40)))
    if shape == 5:
        return "".join(chr(rng.randrange(32, 0x2FF))
                       for _ in range(rng.randrange(0, 12)))
    if shape == 6:
        return tuple(_rand_value(rng, depth + 1)
                     for _ in range(rng.randrange(0, 4)))
    if shape == 7:
        return [_rand_value(rng, depth + 1)
                for _ in range(rng.randrange(0, 4))]
    return {rng.randrange(100): _rand_value(rng, depth + 1)
            for _ in range(rng.randrange(0, 3))}


def _rand_selector(rng) -> si.KeySelector:
    return si.KeySelector(key=bytes(rng.randrange(256)
                                    for _ in range(rng.randrange(0, 8))),
                          or_equal=rng.random() < 0.5,
                          offset=rng.randrange(-3, 4))


def _rand_request(rng):
    """One of the four hot-token request payloads the encoder exists for."""
    shape = rng.randrange(4)
    if shape == 0:
        return si.GetValueRequest(
            key=b"k%d" % rng.randrange(1000), version=rng.randrange(1 << 40))
    if shape == 1:
        return si.GetValuesRequest(
            reads=[(b"k%d" % rng.randrange(1000), rng.randrange(1 << 40))
                   for _ in range(rng.randrange(1, 6))])
    if shape == 2:
        return si.GetKeyValuesRequest(
            begin=_rand_selector(rng), end=_rand_selector(rng),
            version=rng.randrange(1 << 40), limit=rng.randrange(0, 100),
            limit_bytes=rng.randrange(0, 10**6), reverse=rng.random() < 0.5)
    return si.GetReadVersionRequest(
        priority=rng.randrange(3),
        debug_id=None if rng.random() < 0.5 else "grv-%x" % rng.getrandbits(32))


def fuzz_encode_parity(seed: int, iters: int = 80):
    """C batch encoder == Python batch encoder == per-request frame
    concatenation, bit for bit, over hot-token requests and arbitrary
    wire-encodable payloads."""
    rng = random.Random(seed)
    for _ in range(iters):
        items = []
        for _i in range(rng.randrange(1, 9)):
            payload = (_rand_request(rng) if rng.random() < 0.6
                       else _rand_value(rng))
            items.append((rng.getrandbits(64), rng.getrandbits(64), payload))
        got = nt.encode_batch(items)
        assert got == nt.py_encode_batch(items)
        assert got == b"".join(
            nt.py_frame(tok, rid, _REQUEST, wire.dumps(p))
            for tok, rid, p in items)


def test_encode_parity_fuzz():
    for seed in (41, 42):
        fuzz_encode_parity(seed)


def test_encode_unsupported_payload_raises_for_whole_batch():
    """The fallback signal: a payload only the Python codec can express
    (>64-bit int) makes the C encoder raise instead of guessing — and the
    Python encoder (the fallback target) still handles it."""
    items = [(40, 1, si.GetValueRequest(key=b"k", version=1)),
             (40, 2, 1 << 70)]
    with pytest.raises(OverflowError):
        nt.encode_batch(items)
    buf = nt.py_encode_batch(items)
    assert buf.startswith(nt.py_frame(
        40, 1, _REQUEST, wire.dumps(si.GetValueRequest(key=b"k", version=1))))


def test_encode_rejects_malformed_items():
    with pytest.raises(TypeError):
        nt.encode_batch([(1, 2)])  # not a 3-tuple
    with pytest.raises(TypeError):
        nt.encode_batch(7)  # not a sequence


# -- (2) reply pump parity ----------------------------------------------------

def _frames_with_expectations(rng):
    """A random reply stream as (frames, expected_err): frames is a list of
    (frame_bytes, expected_entry_or_None) pairs — each frame is generated
    WITH its expected ClientConn entry, so the parity check pins the C
    decode-vs-raw-fallback decision, not just frame splitting. The last
    frame carries expected_entry None when it is a protocol reject."""
    frames, err = [], None
    for _f in range(rng.randrange(1, 7)):
        rid = rng.getrandbits(64)
        shape = rng.randrange(8)
        if shape == 0:  # decodable reply object
            payload = si.GetValueReply(
                value=None if rng.random() < 0.3 else b"v%d" % rng.randrange(99),
                version=rng.randrange(1 << 40))
            frames.append((nt.py_frame(0, rid, _REPLY, wire.dumps(payload)),
                           (rid, _REPLY, payload, None)))
        elif shape == 1:  # decodable plain value
            payload = _rand_value(rng)
            frames.append((nt.py_frame(0, rid, _REPLY, wire.dumps(payload)),
                           (rid, _REPLY, payload, None)))
        elif shape == 2:  # error reply: bare name or [name, detail]
            payload = ("transaction_too_old" if rng.random() < 0.5
                       else ["transaction_throttled", "backoff=0.05"])
            frames.append((nt.py_frame(0, rid, _REPLY_ERROR,
                                       wire.dumps(payload)),
                           (rid, _REPLY_ERROR, payload, None)))
        elif shape == 3:  # non-reply kind: never decoded, raw passthrough
            kind = rng.choice((_REQUEST, _ONE_WAY, rng.randrange(4, 256)))
            body = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 30)))
            frames.append((nt.py_frame(0, rid, kind, body),
                           (rid, kind, None, body)))
        elif shape == 4:  # reply body without the wire magic: raw fallback
            body = bytes([rng.randrange(256) & ~0x01])  # != 0xF5
            body += bytes(rng.randrange(256)
                          for _ in range(rng.randrange(0, 20)))
            frames.append((nt.py_frame(0, rid, _REPLY, body),
                           (rid, _REPLY, None, body)))
        elif shape == 5:  # decodable value + trailing junk: raw fallback
            body = wire.dumps(rng.randrange(100)) + b"\x00"
            frames.append((nt.py_frame(0, rid, _REPLY, body),
                           (rid, _REPLY, None, body)))
        elif shape == 6:  # >64-bit varint: C declines, Python authority
            body = wire._py_dumps(1 << 70)
            frames.append((nt.py_frame(0, rid, _REPLY, body),
                           (rid, _REPLY, None, body)))
        else:  # protocol rejects end the stream
            frame = nt.py_frame(0, rid, _REPLY, b"xy")
            if rng.random() < 0.5:
                i = rng.randrange(nt.HEADER_LEN - 4, len(frame))
                frame = frame[:i] + bytes([frame[i] ^ 0x20]) + frame[i + 1:]
                err = "packet checksum mismatch"
            else:
                frame = struct.pack(
                    ">I", nt.MAX_FRAME_BYTES + rng.randrange(1, 1 << 20)) \
                    + frame[4:]
                err = "oversized frame"
            frames.append((frame, None))
            break
    return frames, err


def _feed_chunked(conn, data: bytes, rng):
    """Feed a ClientConn in random-size chunks; accumulate (entries, err),
    stopping at the first err (dead-latch contract)."""
    entries, pos = [], 0
    while pos < len(data):
        n = rng.randrange(1, max(2, len(data) - pos + 1))
        got, err = conn.feed(data[pos:pos + n])
        entries.extend(got)
        if err is not None:
            return entries, err
        pos += n
    return entries, None


def fuzz_reply_pump_parity(seed: int, streams: int = 40):
    """ClientConn.feed under random chunking produces exactly the
    entries/reject/residue the generator predicted: decoded payloads where
    the C codec covers the body, raw-bytes fallback where it declines,
    in-band err at the first protocol reject."""
    rng = random.Random(seed)
    for _ in range(streams):
        frames, want_err = _frames_with_expectations(rng)
        data = b"".join(fb for fb, _e in frames)
        expected = [e for _fb, e in frames if e is not None]
        if want_err is None and rng.random() < 0.5:  # torn tail
            want_err = None
            data = data[:max(0, len(data) - rng.randrange(1, 30))]
            expected, consumed = [], 0
            for fb, e in frames:
                if consumed + len(fb) > len(data):
                    break
                expected.append(e)
                consumed += len(fb)
            want_residue = data[consumed:]
        else:
            want_residue = b"" if want_err is None else None
        conn = nt.new_client_conn()
        got, err = _feed_chunked(conn, data, rng)
        assert err == want_err
        assert got == expected
        if want_err is None:
            assert conn.residue() == want_residue


def test_reply_pump_parity_fuzz():
    for seed in (43, 44):
        fuzz_reply_pump_parity(seed)


def test_pump_error_reply_with_detail_decodes():
    body = wire.dumps(["transaction_throttled", "backoff=0.1 hot=k7"])
    conn = nt.new_client_conn()
    entries, err = conn.feed(nt.py_frame(0, 9, _REPLY_ERROR, body))
    assert err is None
    assert entries == [(9, _REPLY_ERROR,
                        ["transaction_throttled", "backoff=0.1 hot=k7"], None)]


def test_pump_dead_latch_and_residue():
    conn = nt.new_client_conn()
    good = nt.py_frame(0, 1, _REPLY, wire.dumps("ok"))
    bad = nt.py_frame(0, 2, _REPLY, b"body")
    bad = bad[:-1] + bytes([bad[-1] ^ 1])
    entries, err = conn.feed(good + bad)
    assert entries == [(1, _REPLY, "ok", None)]
    assert err == "packet checksum mismatch"
    with pytest.raises(ValueError):
        conn.feed(b"more")
    # torn-tail residue on a healthy conn
    conn2 = nt.new_client_conn()
    frame = nt.py_frame(0, 3, _REPLY, wire.dumps(None))
    entries, err = conn2.feed(frame + frame[:10])
    assert err is None and len(entries) == 1
    assert conn2.residue() == frame[:10]


# -- (3) transport settlement -------------------------------------------------

def _free_addr():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return addr


class _SinkWriter:
    """Writer double for the request fast path: collects bytes."""

    def __init__(self):
        self.chunks = []

    def write(self, data):
        self.chunks.append(bytes(data))

    def is_closing(self):
        return False

    def close(self):
        pass


def test_burst_settles_and_cancels_every_timer(monkeypatch):
    """Satellite 1 regression: after a 1k-read burst settles through the
    native reply pump, ZERO request-timeout TimerHandles may remain live —
    each must be cancelled at settlement, not left to expire (1k live 5s
    timers per burst is pure timer-heap churn retaining payloads)."""
    import asyncio

    monkeypatch.setenv("NET_NATIVE_CLIENT", "1")
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net import transport as T

    loop = T.RealEventLoop()
    t = T.NetTransport(loop, "127.0.0.1:1")  # never started: no sockets
    assert t.native_client
    addr = "10.0.0.9:4000"
    w = _SinkWriter()
    peer = loop.aio.create_future()
    peer.set_result(w)
    t._peers[addr] = peer

    n = 1000
    futs = [t.request(t.process, Endpoint(addr, si.Token.STORAGE_GET_VALUE),
                      si.GetValueRequest(key=b"k%d" % i, version=7),
                      timeout=30.0)
            for i in range(n)]
    replies = b"".join(
        nt.py_frame(0, rid, _REPLY,
                    wire.dumps(si.GetValueReply(value=b"v%d" % rid,
                                                version=7)))
        for rid in range(1, n + 1))

    async def pump():
        r = asyncio.StreamReader()
        r.feed_data(replies)
        r.feed_eof()
        await t._native_read_replies(r, addr)

    loop.aio.run_until_complete(pump())

    assert all(f.is_ready() and not f.is_error() for f in futs)
    assert futs[0].get().value == b"v1"
    assert futs[-1].get().value == b"v%d" % n
    # the batched encode actually ran (one C call, no per-request frames)
    assert t._c_client_py_falls == 0
    c = t.transport_counters()
    assert c["ClientNativeBatches"] >= 2  # >=1 send flush + >=1 feed batch
    assert c["ClientNativeSettles"] == n
    assert b"".join(w.chunks) == nt.py_encode_batch(
        [(si.Token.STORAGE_GET_VALUE, i + 1,
          si.GetValueRequest(key=b"k%d" % i, version=7)) for i in range(n)])
    # THE satellite assertion: no live timer handles after settlement
    live = [h for h in loop.aio._scheduled if not h._cancelled]
    assert live == []
    assert not t._pending


def test_settle_batch_routes_errors_and_raw_fallback():
    """_settle_batch: error entries settle as FDBError (detail preserved),
    raw entries decode through Python (ClientPyFalls), dedup'd reply_ids
    are skipped, and an undecodable raw body fails its future AND drops
    the connection."""
    from foundationdb_tpu.core.future import Promise
    from foundationdb_tpu.net import transport as T

    loop = T.RealEventLoop()
    t = T.NetTransport(loop, "127.0.0.1:1")
    ok, err_p, raw_p = Promise(), Promise(), Promise()
    t._pending[1] = (ok, "a:1", None)
    t._pending[2] = (err_p, "a:1", None)
    t._pending[3] = (raw_p, "a:1", None)
    t._settle_batch([
        (1, T._REPLY, "value", None),
        (2, T._REPLY_ERROR, ["transaction_throttled", "backoff=0.2"], None),
        (3, T._REPLY, None, wire.dumps(1 << 70)),  # only Python decodes
        (99, T._REPLY, "dropped", None),  # no pending entry: dedup skip
    ])
    assert ok.future.get() == "value"
    e = err_p.future._result
    assert (e.name, e.detail) == ("transaction_throttled", "backoff=0.2")
    assert raw_p.future.get() == 1 << 70
    assert t._c_client_py_falls == 1
    assert t._c_client_settles == 3

    bad = Promise()
    t._pending[4] = (bad, "a:1", None)
    with pytest.raises(ConnectionError):
        t._settle_batch([(4, T._REPLY, None, b"\xf5\x01garbage")])
    assert bad.future.is_error()
    assert bad.future._result.name == "broken_promise"


def test_native_client_over_real_wire_and_ablation(monkeypatch):
    """End-to-end: a NET_NATIVE_CLIENT=1 client against a pure-Python
    server — values, error replies with detail, and counters — then the
    same calls with the plane off must return identical results (the
    bench's ablation contract)."""
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop

    def run(native_on: str):
        monkeypatch.setenv("NET_NATIVE_CLIENT", native_on)
        loop = RealEventLoop()
        srv = NetTransport(loop, _free_addr())
        cli = NetTransport(loop, _free_addr())
        srv.start()
        cli.start()
        try:
            assert cli.native_client == (native_on == "1")
            from foundationdb_tpu.utils.errors import FDBError

            def on_gvs(req, reply):
                reply.send(si.GetValuesReply(
                    results=[(0, b"=" + k) for k, _v in req.reads]))

            def on_throttle(_req, reply):
                reply.send_error(
                    FDBError("transaction_throttled", "backoff=0.25"))
            srv.process.register(si.Token.STORAGE_GET_VALUES, on_gvs)
            srv.process.register(99, on_throttle)

            async def calls():
                gvs = await cli.request(
                    cli.process,
                    Endpoint(srv.address, si.Token.STORAGE_GET_VALUES),
                    si.GetValuesRequest(reads=[(b"a", 1), (b"b", 1)]))
                try:
                    await cli.request(cli.process,
                                      Endpoint(srv.address, 99), None)
                    raise AssertionError("error reply did not raise")
                except FDBError as e:
                    thr = (e.name, e.detail)
                return gvs.results, thr

            out = loop.run_future(loop.spawn(calls()), max_time=15.0)
            counters = cli.transport_counters()
            return out, counters
        finally:
            srv.close()
            cli.close()

    native_out, nc = run("1")
    assert nc["ClientNativeBatches"] >= 1
    assert nc["ClientNativeSettles"] >= 2
    assert nc["ChecksumRejects"] == 0
    py_out, pc = run("0")
    assert pc["ClientNativeBatches"] == 0 and pc["ClientNativeSettles"] == 0
    assert native_out == py_out
    assert native_out[0] == [(0, b"=a"), (0, b"=b")]
    assert native_out[1] == ("transaction_throttled", "backoff=0.25")


def test_pump_fault_degrades_connection_mid_stream(monkeypatch):
    """The per-connection degradation contract, client side: a reply-pump
    fault downgrades just that connection to the pure-Python reply loop,
    replaying the pump's buffered residue — in-flight requests still get
    their answers."""
    monkeypatch.setenv("NET_NATIVE_CLIENT", "1")
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop

    class FaultyPump:
        def __init__(self):
            self.buf = b""

        def feed(self, chunk):
            self.buf += bytes(chunk)
            raise RuntimeError("injected pump fault")

        def residue(self):
            return self.buf

    monkeypatch.setattr(nt, "new_client_conn", lambda: FaultyPump())

    loop = RealEventLoop()
    srv = NetTransport(loop, _free_addr())
    cli = NetTransport(loop, _free_addr())
    srv.start()
    cli.start()
    try:
        srv.process.register(42, lambda payload, reply: reply.send(
            payload * 2))

        async def call():
            a = await cli.request(cli.process, Endpoint(srv.address, 42), 10)
            b = await cli.request(cli.process, Endpoint(srv.address, 42), 11)
            return a, b
        assert loop.run_future(loop.spawn(call()), max_time=15.0) == (20, 22)
    finally:
        srv.close()
        cli.close()


# -- satellite 2: frame-to-future in one tick ---------------------------------

def test_read_group_settles_same_tick_with_span():
    """The database's single-replica read group settles its batch futures
    synchronously from the request future's callback — no coroutine resume
    between reply arrival and caller settlement — and emits the Client.Read
    span around exactly that window."""
    import types

    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.core.future import Future
    from foundationdb_tpu.utils import trace as T

    captured = {}

    class _Net:
        def request(self, process, ep, payload):
            captured["ep"] = ep
            captured["req"] = payload
            captured["f"] = Future()
            return captured["f"]

    db = object.__new__(Database)
    db.loop = types.SimpleNamespace(now=lambda: 1.0)
    db.process = types.SimpleNamespace(net=_Net())
    db._replica_stats = types.SimpleNamespace(
        record=lambda addr, dt: None,
        begin=lambda addr: None, end=lambda addr: None)
    db.coordinators = None
    db._team_order = lambda team: team
    db._next_span_id = lambda kind: "r-tick"

    ents = [(b"a", 7, Future()), (b"b", 7, Future())]
    coro = db._send_read_group(["s1:1"], ents)
    with pytest.raises(StopIteration):
        coro.send(None)  # the fast path runs to completion without awaiting
    assert captured["req"].reads == [(b"a", 7), (b"b", 7)]
    assert not any(f.is_ready() for _k, _v, f in ents)

    n0 = len(T.g_trace_batch._events)
    reply = types.SimpleNamespace(results=[(0, b"va"), (0, None)])
    captured["f"]._set(reply)  # the reply frame "arrives"
    # settled NOW, same tick — no event loop ever ran in this test
    assert [f.get() for _k, _v, f in ents] == [b"va", None]
    spans = [e for e in T.g_trace_batch._events[n0:]
             if e.get("Span") == "Client.Read" and e.get("ID") == "r-tick"]
    assert [s["Phase"] for s in spans] == ["Begin", "End"]

    # error arrival settles the whole batch in the same tick too
    ents2 = [(b"c", 7, Future())]
    coro = db._send_read_group(["s1:1"], ents2)
    with pytest.raises(StopIteration):
        coro.send(None)
    captured["f"]._set_error(RuntimeError("replica exploded"))
    assert ents2[0][2].is_error()


def test_get_many_without_read_version_chains_grv():
    """Transaction.get_many with no read version fetches the GRV once and
    chains the multiget off its callback — no per-key coroutine fan-out —
    and the result future settles synchronously from the reply callback."""
    import types

    from foundationdb_tpu.client.transaction import Transaction
    from foundationdb_tpu.core.future import Future

    grvf, readf = Future(), Future()
    calls = []
    db = types.SimpleNamespace(
        _grv=lambda: calls.append("grv") or grvf,
        _read_get_many=lambda keys, v: calls.append(("read", keys, v))
        or readf)

    tr = object.__new__(Transaction)
    tr.db = db
    tr._opt_timeout_ms = None
    tr.reset()

    out = tr.get_many([b"a", b"b"])
    assert calls == ["grv"]  # read not issued until the GRV lands
    grvf._set(types.SimpleNamespace(version=99))
    assert tr._read_version == 99
    assert calls[1] == ("read", [b"a", b"b"], 99)
    assert not out.is_ready()
    readf._set([b"va", b"vb"])
    assert out.get() == [b"va", b"vb"]  # same tick: no loop ran
    assert tr._read_conflict_keys == [b"a", b"b"]

    # get_future rides the same chain
    grvf2, readf2 = Future(), Future()
    db._grv = lambda: grvf2
    db._read_get = lambda key, v: readf2
    tr2 = object.__new__(Transaction)
    tr2.db = db
    tr2._opt_timeout_ms = None
    tr2.reset()
    f = tr2.get_future(b"k")
    grvf2._set(types.SimpleNamespace(version=5))
    readf2._set(b"v")
    assert f.get() == b"v"


# -- satellite 5: PROTO005 pins for the client-encoded request structs --------

def _real_c_source() -> str:
    import os

    from foundationdb_tpu.analysis import flowlint
    path = os.path.join(flowlint.default_target(), "native", "fdb_native.c")
    with open(path, encoding="utf-8") as f:
        return f.read()


_REQ_NAMES = ("GetValueRequest", "GetValuesRequest", "GetKeyValuesRequest",
              "GetReadVersionRequest")


def _req_py_view():
    import dataclasses
    py_fields = {n: [f.name for f in dataclasses.fields(getattr(si, n))]
                 for n in _REQ_NAMES}
    return py_fields, set(_REQ_NAMES)


def test_proto005_parses_client_request_pins():
    from foundationdb_tpu.analysis import protolint
    schemas = {s.name: s for s in protolint.parse_c_schemas(_real_c_source())}
    assert schemas["GetValueRequest"].fields == ["key", "version"]
    assert schemas["GetValuesRequest"].fields == ["reads"]
    assert schemas["GetKeyValuesRequest"].fields == [
        "begin", "end", "version", "limit", "limit_bytes", "reverse"]
    assert schemas["GetReadVersionRequest"].fields == [
        "priority", "debug_id", "count"]


def test_proto005_request_parity_holds_on_the_real_tree():
    from foundationdb_tpu.analysis import protolint
    py_fields, registered = _req_py_view()
    assert protolint.c_parity_problems(
        protolint.parse_c_schemas(_real_c_source()), py_fields,
        registered) == []


def test_proto005_trips_when_request_pin_drifts():
    """Mutation-proof: grow the C pin by a field the dataclass lacks and
    the parity rule must flag it (same gate as the reply structs)."""
    from foundationdb_tpu.analysis import protolint
    src = _real_c_source().replace(
        "GetValueRequest { key", "GetValueRequest { shard_hint, key")
    assert src != _real_c_source()
    py_fields, registered = _req_py_view()
    problems = protolint.c_parity_problems(
        protolint.parse_c_schemas(src), py_fields, registered)
    assert any(s.name == "GetValueRequest" and "mis-fills" in m
               for s, m in problems)


def test_proto005_trips_when_python_request_gains_a_field():
    from foundationdb_tpu.analysis import protolint
    py_fields, registered = _req_py_view()
    py_fields["GetValuesRequest"] = py_fields["GetValuesRequest"] + ["hint"]
    problems = protolint.c_parity_problems(
        protolint.parse_c_schemas(_real_c_source()), py_fields, registered)
    assert any(s.name == "GetValuesRequest" and "mis-fills" in m
               for s, m in problems)
