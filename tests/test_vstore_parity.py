"""Parity fuzz: the C VStore read path vs the pure-Python VersionedMap.

The native store (native/fdb_native.c VStore, wrapped by NativeVersionedMap)
must be observationally identical to VersionedMap — it is chosen silently by
make_versioned_map(), so any divergence is a storage-corruption bug. The fuzz
drives both through identical mutation/clear/GC interleavings and then
cross-checks every read surface at random versions: point gets, batched gets
(including transaction_too_old results), all four key-selector base forms
with offsets, range reads with limit/byte-limit/reverse, and the wire frames
the C store emits directly (must byte-equal the canonical Python codec's
encoding of the fallback's reply).
"""

from __future__ import annotations

import random

import pytest

from foundationdb_tpu import native
from foundationdb_tpu.server.interfaces import (
    GetKeyValuesReply, GetValuesReply, KeySelector)
from foundationdb_tpu.server.versioned_map import (
    NativeVersionedMap, VersionedMap, make_versioned_map)
from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.types import Mutation, MutationType

HAVE_NATIVE = native.available() and hasattr(native.mod, "VStore")

KEYSPACE = [b"k%03d" % i for i in range(40)] + [b"", b"\x00", b"\xfe\xff"]


def _rand_key(rng: random.Random) -> bytes:
    return rng.choice(KEYSPACE)


def _rand_value(rng: random.Random) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 24)))


def _mutate_both(rng: random.Random, maps, version: int):
    roll = rng.random()
    if roll < 0.55:
        m = Mutation(MutationType.SET_VALUE, _rand_key(rng), _rand_value(rng))
    elif roll < 0.75:
        a, b = _rand_key(rng), _rand_key(rng)
        if a > b:
            a, b = b, a
        m = Mutation(MutationType.CLEAR_RANGE, a, b + b"\x00")
    elif roll < 0.9:
        op = rng.choice([MutationType.ADD_VALUE, MutationType.BYTE_MAX,
                         MutationType.APPEND_IF_FITS])
        m = Mutation(op, _rand_key(rng), _rand_value(rng)[:8])
    else:
        m = Mutation(MutationType.SET_VALUE, _rand_key(rng), None)
        m = Mutation(MutationType.CLEAR_RANGE, m.param1, m.param1 + b"\x00")
    for vm in maps:
        vm.apply(version, m)


def _check_reads(rng: random.Random, py: VersionedMap, nat, version: int):
    key = _rand_key(rng)
    assert py.get(key, version) == nat.get(key, version)

    reads = [(_rand_key(rng), rng.randrange(max(0, version - 30), version + 1))
             for _ in range(rng.randrange(1, 6))]
    assert py.get_batch(reads) == nat.get_batch(reads)

    sel = KeySelector(key=_rand_key(rng), or_equal=rng.random() < 0.5,
                      offset=rng.randrange(-3, 4))
    assert py.resolve_selector(sel, version) == nat.resolve_selector(
        sel, version), sel

    a, b = _rand_key(rng), _rand_key(rng)
    if a > b:
        a, b = b, a
    limit = rng.choice([0, 1, 2, 5])
    limit_bytes = rng.choice([0, 0, 30, 200])
    reverse = rng.random() < 0.3
    assert py.range_read(a, b + b"\x00", version, limit, limit_bytes,
                         reverse) == nat.range_read(
        a, b + b"\x00", version, limit, limit_bytes, reverse)


def _check_encoded(rng: random.Random, py: VersionedMap, nat, version: int):
    """The C store's one-pass wire frames must byte-equal the canonical
    Python codec run over the fallback's reply objects."""
    reads = [(_rand_key(rng), rng.randrange(max(0, version - 30), version + 1))
             for _ in range(rng.randrange(1, 6))]
    frame = nat.get_batch_encoded(reads)
    assert frame == wire._py_dumps(GetValuesReply(results=py.get_batch(reads)))
    assert wire.loads(frame) == GetValuesReply(results=py.get_batch(reads))

    a, b = _rand_key(rng), _rand_key(rng)
    if a > b:
        a, b = b, a
    limit, reverse = rng.choice([0, 3]), rng.random() < 0.3
    data, more = py.range_read(a, b + b"\x00", version, limit, 0, reverse)
    frame = nat.range_read_encoded(a, b + b"\x00", version, limit, 0, reverse)
    assert frame == wire._py_dumps(
        GetKeyValuesReply(data=data, more=more, version=version))


@pytest.mark.skipif(not HAVE_NATIVE, reason="C extension unavailable")
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_vstore_parity_fuzz(seed):
    rng = random.Random(seed)
    py = VersionedMap()
    nat = NativeVersionedMap()
    version = 0
    for step in range(1200):
        roll = rng.random()
        if roll < 0.45:
            version += rng.randrange(1, 4)
            _mutate_both(rng, (py, nat), version)
        elif roll < 0.5 and version > 0:
            v = rng.randrange(0, version + 1)
            py.forget_before(v)
            nat.forget_before(v)
            assert py.oldest_version == nat.oldest_version
        elif roll < 0.53 and version > 0:
            v = rng.randrange(max(0, version - 10), version + 1)
            py.rollback(v)
            nat.rollback(v)
            version = max(py.latest_version, py.oldest_version)
            assert py.latest_version == nat.latest_version
        else:
            _check_reads(rng, py, nat, rng.randrange(
                py.oldest_version, version + 1) if version else 0)
        if step % 97 == 0:
            assert py.key_count() == nat.key_count()
            assert py.byte_size() == nat.byte_size()
    assert py.key_count() == nat.key_count()
    assert py.byte_size() == nat.byte_size()


@pytest.mark.skipif(not HAVE_NATIVE, reason="C extension unavailable")
def test_vstore_too_old_parity():
    py, nat = VersionedMap(), NativeVersionedMap()
    for vm in (py, nat):
        vm.apply(5, Mutation(MutationType.SET_VALUE, b"a", b"1"))
        vm.forget_before(5)
    for vm in (py, nat):
        with pytest.raises(FDBError) as ei:
            vm.get(b"a", 3)
        assert ei.value.name == "transaction_too_old"
        with pytest.raises(FDBError):
            vm.range_read(b"", b"z", 3)
        with pytest.raises(FDBError):
            vm.resolve_selector(KeySelector(b"a", False, 1), 3)
    # batched gets report staleness per-key, not as a batch error
    assert py.get_batch([(b"a", 3), (b"a", 5)]) \
        == nat.get_batch([(b"a", 3), (b"a", 5)]) \
        == [(1, "transaction_too_old"), (0, b"1")]


@pytest.mark.skipif(not HAVE_NATIVE, reason="C extension unavailable")
@pytest.mark.parametrize("seed", [11, 12])
def test_vstore_encoded_reply_parity(seed):
    rng = random.Random(seed)
    py = VersionedMap()
    nat = NativeVersionedMap()
    version = 0
    for _ in range(300):
        if rng.random() < 0.5:
            version += rng.randrange(1, 3)
            _mutate_both(rng, (py, nat), version)
        elif version:
            _check_encoded(rng, py, nat,
                           rng.randrange(py.oldest_version, version + 1))


def test_selector_forms_parity():
    """All four KeySelector base forms (FDBTypes.h) ± offsets, against a
    fixed store — runs on the Python fallback alone when the extension is
    missing, so selector semantics stay pinned either way."""
    maps = [VersionedMap()]
    if HAVE_NATIVE:
        maps.append(NativeVersionedMap())
    for vm in maps:
        for i, k in enumerate([b"a", b"c", b"e", b"g"]):
            vm.apply(i + 1, Mutation(MutationType.SET_VALUE, k, b"v"))
        vm.apply(5, Mutation(MutationType.CLEAR_RANGE, b"e", b"e\x00"))
    cases = []
    for key in [b"", b"a", b"b", b"c", b"e", b"g", b"z"]:
        for or_equal, offset in [(False, 1), (True, 1),   # fge / fgt
                                 (True, 0), (False, 0),   # lle / llt
                                 (False, 3), (True, -2), (False, -1)]:
            cases.append(KeySelector(key, or_equal, offset))
    expect = {
        (b"b", False, 1): b"c",   # first_greater_or_equal(b) -> c
        (b"c", True, 1): b"g",    # first_greater_than(c) skips cleared e
        (b"e", True, 0): b"c",    # last_less_or_equal(e): e is cleared
        (b"z", False, 0): b"g",   # last_less_than(z)
        (b"z", False, 1): b"\xff\xff",
        (b"", False, 0): b"",
    }
    for sel in cases:
        results = [vm.resolve_selector(sel, 5) for vm in maps]
        assert all(r == results[0] for r in results), sel
        want = expect.get((sel.key, sel.or_equal, sel.offset))
        if want is not None:
            assert results[0] == want, sel


def test_python_fallback_always_constructible():
    """make_versioned_map must hand back a working store even when the
    extension is absent (the factory's whole point)."""
    vm = make_versioned_map()
    vm.apply(1, Mutation(MutationType.SET_VALUE, b"k", b"v"))
    assert vm.get(b"k", 1) == b"v"
    # and the pure-Python class itself serves the same surface
    py = VersionedMap()
    py.apply(1, Mutation(MutationType.SET_VALUE, b"k", b"v"))
    assert py.get_batch([(b"k", 1)]) == [(0, b"v")]
    assert py.resolve_selector(KeySelector(b"", False, 1), 1) == b"k"
