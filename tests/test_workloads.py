"""The five high-value reference workloads, each composed with faults in
seeded specs (VERDICT r4 ask 4).

Reference: fdbserver/workloads/ConflictRange.actor.cpp (resolver oracle),
ApiCorrectness.actor.cpp, WriteDuringRead.actor.cpp, AtomicOps.actor.cpp,
RandomMoveKeys.actor.cpp; composed like tests/fast/*.txt specs (a
correctness workload + RandomClogging and/or Attrition, fixed seed).
"""

import pytest

from foundationdb_tpu.testing import (
    ApiCorrectnessWorkload, AtomicOpsWorkload, AttritionWorkload,
    ConflictRangeWorkload, ConsistencyCheckWorkload, CycleWorkload,
    RandomCloggingWorkload, RandomMoveKeysWorkload, WriteDuringReadWorkload,
    run_spec)
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def test_conflict_range_with_clogging():
    """The system-level resolver oracle: every A/B transaction pair's
    conflict verdict matches the host-side expectation, under clogging."""
    w = ConflictRangeWorkload()
    run_spec(61, workloads=[w, RandomCloggingWorkload()], duration=40.0,
             buggify=False)
    assert w.checked > 10 and w.conflicts > 0


def test_api_correctness_with_clogging():
    w = ApiCorrectnessWorkload()
    run_spec(62, workloads=[w, RandomCloggingWorkload()], duration=40.0,
             buggify=False)
    assert w.txns > 5


def test_write_during_read_with_clogging():
    w = WriteDuringReadWorkload()
    run_spec(63, workloads=[w, RandomCloggingWorkload()], duration=40.0,
             buggify=False)
    assert w.txns > 5


def test_atomic_ops_with_clogging_and_attrition():
    w = AtomicOpsWorkload()
    run_spec(64, workloads=[w, RandomCloggingWorkload(),
                            AttritionWorkload(interval=10.0)],
             duration=45.0, buggify=False)
    assert w.attempted > 10


def test_random_move_keys_with_cycle_and_faults():
    w = RandomMoveKeysWorkload(interval=2.0)
    run_spec(65, workloads=[CycleWorkload(), w, RandomCloggingWorkload(),
                            ConsistencyCheckWorkload()],
             duration=45.0, buggify=False, n_replicas=2,
             n_storage_workers=4)
    assert w.moves > 0


def test_increment_with_clogging():
    from foundationdb_tpu.testing import IncrementWorkload
    w = IncrementWorkload()
    run_spec(66, workloads=[w, RandomCloggingWorkload()], duration=35.0,
             buggify=False)
    assert w.confirmed > 10


def test_selector_correctness_with_clogging():
    from foundationdb_tpu.testing import SelectorCorrectnessWorkload
    w = SelectorCorrectnessWorkload()
    run_spec(67, workloads=[w, RandomCloggingWorkload()], duration=30.0,
             buggify=False)


def test_watches_with_clogging():
    from foundationdb_tpu.testing import WatchesWorkload
    w = WatchesWorkload()
    run_spec(68, workloads=[w, RandomCloggingWorkload()], duration=35.0,
             buggify=False)
    assert w.fired > 3


def test_versionstamp_workload():
    from foundationdb_tpu.testing import VersionStampWorkload
    w = VersionStampWorkload()
    run_spec(69, workloads=[w], duration=25.0, buggify=False)
    assert len(w.stamps) > 5
