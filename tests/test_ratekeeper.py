"""Ratekeeper admission control + TLog memory bounds.

Reference: fdbserver/Ratekeeper.actor.cpp updateRate (:250) / rateKeeper
(:508); TLogServer.actor.cpp spill (updatePersistentData :548) and bounded
peek replies. Nothing may grow without bound when a storage server lags:
the TLog spills to its durable queue and the ratekeeper throttles ingest.
"""

import pytest

from foundationdb_tpu.server.cluster import RecoverableCluster, SimCluster
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def test_tlog_spill_and_bounded_peek_with_lagging_storage():
    """A storage cut off from the TLogs makes the log queue grow; the TLog
    must spill (bounded memory) and, once the storage is healed, serve the
    spilled versions back through bounded peek pages with no lost data."""
    KNOBS.set("TLOG_SPILL_BYTES", 2_000)
    KNOBS.set("TLOG_PEEK_REPLY_BYTES", 500)
    c = SimCluster(seed=7, n_tlogs=1, n_storage=1)
    db = c.database()
    tlog = c.tlogs[0]
    storage_addr = c.storage_procs[0].address
    tlog_addr = c.tlog_procs[0].address

    async def t():
        # cut the storage off from the log so it cannot pop
        c.net.partition(storage_addr, tlog_addr)
        c.net.partition(tlog_addr, storage_addr)

        async def writes(tr):
            for i in range(40):
                tr.set(b"k%03d" % i, b"x" * 50)
        await db.transact(writes)
        async def writes2(tr):
            for i in range(40, 80):
                tr.set(b"k%03d" % i, b"x" * 50)
        await db.transact(writes2)

        assert tlog._mem_bytes <= KNOBS.TLOG_SPILL_BYTES, \
            f"TLog memory unbounded: {tlog._mem_bytes}"
        assert tlog._mem_floor.get(0, 0) > 0, "nothing was spilled"

        # heal; the storage catches up from the spilled + in-memory ranges
        c.net.heal()
        tr = db.create_transaction()
        rows = await tr.get_range(b"k", b"l")
        assert len(rows) == 80
        assert rows[0] == (b"k000", b"x" * 50)
        assert rows[-1] == (b"k079", b"x" * 50)

    c.run(c.loop.spawn(t()), max_time=10_000.0)


def test_ratekeeper_throttles_on_log_backlog_and_recovers():
    """A storage server that stops consuming makes the TLog's un-popped
    byte queue grow past its target; the ratekeeper cuts the TPS budget,
    and after the cluster heals it returns to the base rate (updateRate's
    proportional control)."""
    KNOBS.set("RK_TARGET_TLOG_BYTES", 500)
    c = RecoverableCluster(seed=21, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1)
    db = c.database()

    def rk():
        cc = c.current_cc()
        if cc is None or cc.dbinfo.ratekeeper is None:
            return None
        proc = c.net.processes[cc.dbinfo.ratekeeper]
        return proc.worker.roles.get("ratekeeper")

    async def t():
        await db.refresh()

        async def write(tr):
            tr.set(b"a", b"1")
        await db.transact(write)

        # cut the storage off from the TLogs: its durability lag now grows
        # with every committed version
        info = c.current_cc().dbinfo
        saddr = info.storages[0][0]
        for t_addr in info.log_epochs[-1].addrs:
            c.net.partition(saddr, t_addr)
            c.net.partition(t_addr, saddr)
        # keep committing blind writes (no reads -> no storage dependency)
        for i in range(30):
            async def w(tr, i=i):
                tr.set(b"k%02d" % i, b"v" * 30)
            await db.transact(w)
            await c.loop.delay(0.5)

        r = rk()
        assert r is not None
        throttled_tps = r.tps
        assert r.stats["worst_tlog_bytes"] > KNOBS.RK_TARGET_TLOG_BYTES
        assert throttled_tps < 0.9 * KNOBS.RK_BASE_TPS, \
            f"no throttling: {throttled_tps}"

        c.net.heal()
        for _ in range(60):
            if rk() and rk().tps > 0.9 * KNOBS.RK_BASE_TPS:
                break
            await c.loop.delay(0.5)
        assert rk().tps > 0.9 * KNOBS.RK_BASE_TPS, "rate did not recover"

    c.run(c.loop.spawn(t()), max_time=60_000.0)


def _proxy_role(c):
    info = c.current_cc().dbinfo
    roles = c.net.processes[info.proxies[0]].worker.roles
    return next(r for k, r in roles.items() if k.startswith("proxy:"))


def test_grv_bucket_saturation_gates_handouts_and_recovers():
    """Drive the proxy's GRV token bucket (proxy.py transactionStarter) to
    saturation: with a tiny TPS budget, a burst of read-version requests
    must overflow the bucket into the wait queue (handout gating), the
    rate reply must have propagated proxy-side, and the queue must drain
    at roughly the budgeted rate once the burst stops (recovery)."""
    KNOBS.set("RK_BASE_TPS", 10.0)
    c = RecoverableCluster(seed=31, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1)
    db = c.database()

    async def t():
        await db.refresh()
        proxy = _proxy_role(c)
        # rate-reply propagation: the proxy learned its share of the budget
        for _ in range(20):
            if proxy._rk_tps is not None:
                break
            await c.loop.delay(0.5)
        assert proxy._rk_tps is not None, "rate reply never propagated"
        assert proxy._rk_tps <= KNOBS.RK_BASE_TPS + 1e-9

        # the bucket caps at a 0.2s burst (2 tokens at 10 tps): a burst of
        # 30 raw GRV requests (bypassing the client batcher, which would
        # coalesce them) must saturate it and queue the overflow
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.server.interfaces import (
            GetReadVersionRequest, Token)
        ep = Endpoint(c.current_cc().dbinfo.proxies[0],
                      Token.PROXY_GET_READ_VERSION)
        t0 = c.loop.now()
        futs = [c.net.request(db.process, ep, GetReadVersionRequest())
                for _ in range(30)]
        await c.loop.delay(0.2)
        assert len(proxy._grv_queue) > 0, "bucket never saturated"
        for f in futs:
            await f
        elapsed = c.loop.now() - t0
        # 30 handouts through a 10/s bucket: >= ~2s of gated release
        assert elapsed >= 2.0, f"handouts were not gated: {elapsed:.2f}s"

        # recovery: with the burst done, the queue drains to empty and a
        # fresh single GRV is served promptly from replenished tokens
        await c.loop.delay(0.5)
        assert not proxy._grv_queue
        t1 = c.loop.now()
        await c.net.request(db.process, ep, GetReadVersionRequest())
        assert c.loop.now() - t1 < 1.0, "bucket did not recover"

    c.run(c.loop.spawn(t()), max_time=60_000.0)


def _contended_load(c, db, stop_at, n_actors=16):
    """Spawn n_actors clients hammering read-modify-write on ONE hot key
    through db.transact (the retry loop under test); returns the tasks."""
    async def actor(i):
        while c.loop.now() < stop_at:
            async def rmw(tr):
                v = await tr.get(b"hot")
                tr.set(b"hot", (int(v or b"0") + 1).__str__().encode())
            try:
                await db.transact(rmw)
            except FDBError:
                pass  # infrastructure noise: keep hammering
    return [c.loop.spawn(actor(i), f"hammer{i}") for i in range(n_actors)]


def test_contention_loop_throttles_end_to_end():
    """The tentpole loop, closed under sim: resolver conflict sampling ->
    ratekeeper throttle list -> proxy transaction_throttled rejections ->
    client penalty cache. Asserts every hop observable."""
    KNOBS.set("RK_THROTTLE_CONFLICT_RATE", 2.0)
    KNOBS.set("RK_THROTTLE_RELEASE_TPS", 4.0)
    c = RecoverableCluster(seed=11, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1)
    db = c.database()

    def rk():
        cc = c.current_cc()
        proc = c.net.processes[cc.dbinfo.ratekeeper]
        return proc.worker.roles.get("ratekeeper")

    async def t():
        await db.refresh()
        tasks = _contended_load(c, db, stop_at=c.loop.now() + 10.0)
        await c.loop.delay(12.0)
        for task in tasks:
            await task
        # detection: the resolver sampled conflicts into its sketch
        info = c.current_cc().dbinfo
        res = c.net.processes[info.resolvers[0]].worker.roles.get("resolver")
        assert res.counters.as_dict()["ConflictsSampled"] > 0
        assert len(res.hot_sketch) > 0
        # throttling: the ratekeeper computed a throttle list at some point
        # (it may have emptied again after load stopped and decay kicked in)
        keeper = rk()
        assert keeper.counters.as_dict()["UpdateRounds"] > 0
        throttled = _proxy_role(c).counters.as_dict()["TxnThrottled"]
        assert throttled > 0, "proxy never rejected with transaction_throttled"
        # informed retry: the advised backoff landed in the penalty cache
        assert db._range_penalties or throttled > 0

    c.run(c.loop.spawn(t()), max_time=120_000.0)


def test_throttle_disabled_knob_keeps_old_behavior():
    """CONTENTION_THROTTLE_ENABLED=False: same contended load, zero
    throttle rejections — the bench's off-row contract."""
    KNOBS.set("CONTENTION_THROTTLE_ENABLED", False)
    KNOBS.set("RK_THROTTLE_CONFLICT_RATE", 2.0)
    c = RecoverableCluster(seed=11, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1)
    db = c.database()

    async def t():
        await db.refresh()
        tasks = _contended_load(c, db, stop_at=c.loop.now() + 6.0, n_actors=8)
        await c.loop.delay(8.0)
        for task in tasks:
            await task
        assert _proxy_role(c).counters.as_dict()["TxnThrottled"] == 0
        assert not db._range_penalties

    c.run(c.loop.spawn(t()), max_time=120_000.0)


def _decision_log(seed: int) -> list:
    """Boot a contended cluster and capture every throttle/split decision
    (RkThrottleList + DDConflictSplit trace events) for `seed`."""
    from foundationdb_tpu.utils import trace as tracemod
    KNOBS.set("RK_THROTTLE_CONFLICT_RATE", 2.0)
    KNOBS.set("RK_THROTTLE_RELEASE_TPS", 4.0)
    events: list = []
    old_sink = tracemod._sink
    tracemod.set_sink(lambda e: events.append(dict(e)))
    try:
        c = RecoverableCluster(seed=seed, n_workers=4, n_proxies=1,
                               n_tlogs=1, n_storage=1)
        # trace timestamps on the SIM clock: decisions must land at the
        # same virtual time in both runs, not just in the same order
        tracemod.set_clock(c.loop.now)
        db = c.database()

        async def t():
            await db.refresh()
            tasks = _contended_load(c, db, stop_at=c.loop.now() + 8.0,
                                    n_actors=12)
            await c.loop.delay(10.0)
            for task in tasks:
                await task

        c.run(c.loop.spawn(t()), max_time=120_000.0)
    finally:
        import time
        tracemod.set_sink(old_sink)
        tracemod.set_clock(time.time)
    return [e for e in events
            if e.get("Type") in ("RkThrottleList", "DDConflictSplit")]


def test_throttle_decisions_deterministic_across_runs():
    """Acceptance criterion: the same sim seed produces the IDENTICAL
    sequence of throttle/split decisions, timestamps included."""
    a = _decision_log(seed=17)
    KNOBS.reset()
    b = _decision_log(seed=17)
    assert a, "contended run produced no throttle decisions to compare"
    assert a == b
