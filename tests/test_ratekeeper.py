"""Ratekeeper admission control + TLog memory bounds.

Reference: fdbserver/Ratekeeper.actor.cpp updateRate (:250) / rateKeeper
(:508); TLogServer.actor.cpp spill (updatePersistentData :548) and bounded
peek replies. Nothing may grow without bound when a storage server lags:
the TLog spills to its durable queue and the ratekeeper throttles ingest.
"""

import pytest

from foundationdb_tpu.server.cluster import RecoverableCluster, SimCluster
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def test_tlog_spill_and_bounded_peek_with_lagging_storage():
    """A storage cut off from the TLogs makes the log queue grow; the TLog
    must spill (bounded memory) and, once the storage is healed, serve the
    spilled versions back through bounded peek pages with no lost data."""
    KNOBS.set("TLOG_SPILL_BYTES", 2_000)
    KNOBS.set("TLOG_PEEK_REPLY_BYTES", 500)
    c = SimCluster(seed=7, n_tlogs=1, n_storage=1)
    db = c.database()
    tlog = c.tlogs[0]
    storage_addr = c.storage_procs[0].address
    tlog_addr = c.tlog_procs[0].address

    async def t():
        # cut the storage off from the log so it cannot pop
        c.net.partition(storage_addr, tlog_addr)
        c.net.partition(tlog_addr, storage_addr)

        async def writes(tr):
            for i in range(40):
                tr.set(b"k%03d" % i, b"x" * 50)
        await db.transact(writes)
        async def writes2(tr):
            for i in range(40, 80):
                tr.set(b"k%03d" % i, b"x" * 50)
        await db.transact(writes2)

        assert tlog._mem_bytes <= KNOBS.TLOG_SPILL_BYTES, \
            f"TLog memory unbounded: {tlog._mem_bytes}"
        assert tlog._mem_floor.get(0, 0) > 0, "nothing was spilled"

        # heal; the storage catches up from the spilled + in-memory ranges
        c.net.heal()
        tr = db.create_transaction()
        rows = await tr.get_range(b"k", b"l")
        assert len(rows) == 80
        assert rows[0] == (b"k000", b"x" * 50)
        assert rows[-1] == (b"k079", b"x" * 50)

    c.run(c.loop.spawn(t()), max_time=10_000.0)


def test_ratekeeper_throttles_on_log_backlog_and_recovers():
    """A storage server that stops consuming makes the TLog's un-popped
    byte queue grow past its target; the ratekeeper cuts the TPS budget,
    and after the cluster heals it returns to the base rate (updateRate's
    proportional control)."""
    KNOBS.set("RK_TARGET_TLOG_BYTES", 500)
    c = RecoverableCluster(seed=21, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1)
    db = c.database()

    def rk():
        cc = c.current_cc()
        if cc is None or cc.dbinfo.ratekeeper is None:
            return None
        proc = c.net.processes[cc.dbinfo.ratekeeper]
        return proc.worker.roles.get("ratekeeper")

    async def t():
        await db.refresh()

        async def write(tr):
            tr.set(b"a", b"1")
        await db.transact(write)

        # cut the storage off from the TLogs: its durability lag now grows
        # with every committed version
        info = c.current_cc().dbinfo
        saddr = info.storages[0][0]
        for t_addr in info.log_epochs[-1].addrs:
            c.net.partition(saddr, t_addr)
            c.net.partition(t_addr, saddr)
        # keep committing blind writes (no reads -> no storage dependency)
        for i in range(30):
            async def w(tr, i=i):
                tr.set(b"k%02d" % i, b"v" * 30)
            await db.transact(w)
            await c.loop.delay(0.5)

        r = rk()
        assert r is not None
        throttled_tps = r.tps
        assert r.stats["worst_tlog_bytes"] > KNOBS.RK_TARGET_TLOG_BYTES
        assert throttled_tps < 0.9 * KNOBS.RK_BASE_TPS, \
            f"no throttling: {throttled_tps}"

        c.net.heal()
        for _ in range(60):
            if rk() and rk().tps > 0.9 * KNOBS.RK_BASE_TPS:
                break
            await c.loop.delay(0.5)
        assert rk().tps > 0.9 * KNOBS.RK_BASE_TPS, "rate did not recover"

    c.run(c.loop.spawn(t()), max_time=60_000.0)
