"""Client read load balance: latency-aware replica choice + backup requests.

Reference: fdbrpc/LoadBalance.actor.h:159 — loadBalance sends the first
request to the best replica per the QueueModel and a duplicate "backup
request" to the next alternative once the first has been in flight longer
than its expected latency; fdbrpc/QueueModel.h smooths per-replica latency.

The headline test clogs ONE replica of a 2-replica team for the whole run:
with random-first-replica every other read would stall behind the clog
(read p99 ~ clog duration); with the EWMA model + hedging the p99 must
collapse to a few backup-delays.
"""

import random

import pytest

from foundationdb_tpu.client.database import ReplicaStats
from foundationdb_tpu.server.cluster import RecoverableCluster
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield
    KNOBS.reset()


def test_replica_stats_ordering():
    stats = ReplicaStats()
    rng = random.Random(7)
    for _ in range(20):
        stats.record("fast", 0.001)
        stats.record("slow", 0.5)
    # jitter is ±20%, a 500x gap cannot flip the order
    for _ in range(50):
        assert stats.order(["slow", "fast"], rng)[0] == "fast"
    # unknown replicas inherit the best estimate: they stay competitive
    order = stats.order(["slow", "fresh", "fast"], rng)
    assert order.index("fresh") < order.index("slow")


def test_replica_stats_ewma_converges():
    stats = ReplicaStats()
    stats.record("a", 1.0)
    for _ in range(60):
        stats.record("a", 0.002)
    assert stats.expected("a", 0.0) < 0.01  # forgot the cold-start spike


def test_clogged_replica_read_p99_collapses():
    """One clogged replica must not poison the read tail: the first slow
    encounter triggers a backup request (hedge), the EWMA then routes
    everything to the healthy replica, and read p99 stays orders of
    magnitude below the clog delay."""
    c = RecoverableCluster(seed=53, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1, n_replicas=2, n_storage_workers=2)
    db = c.database()
    latencies: list[float] = []

    async def t():
        await db.refresh()

        async def setup(tr):
            for i in range(10):
                tr.set(b"lb%02d" % i, b"v%02d" % i)
        await db.transact(setup)

        team, _end = db.locations.locate(b"lb00")
        assert len(team) == 2, f"expected a 2-replica team, got {team}"
        # clog the client <-> replica link for the entire test: every read
        # sent there waits ~clog seconds (sim clogs delay, not drop)
        c.net.clog_pair(db.process.address, team[0], 600.0)

        for i in range(120):
            t0 = c.loop.now()
            tr = db.create_transaction()
            v = await tr.get(b"lb%02d" % (i % 10))
            assert v == b"v%02d" % (i % 10)
            latencies.append(c.loop.now() - t0)

    c.run(c.loop.spawn(t()), max_time=30_000.0)

    latencies.sort()
    p99 = latencies[int(len(latencies) * 0.99)]
    # random-first-replica would put ~half the reads behind the clog
    # (p50 ~ minutes); hedged + EWMA-routed reads finish in milliseconds
    assert p99 < 0.25, f"read p99 {p99:.3f}s did not collapse: {latencies[-5:]}"
    assert latencies[len(latencies) // 2] < 0.05, "median read slow"
