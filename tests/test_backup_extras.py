"""Blob-store backup container + point-in-time restore into a live cluster.

Reference: fdbrpc/BlobStore.actor.cpp + HTTP.actor.cpp (the remote object
container, round 4 VERDICT ask 9) and Restore.actor.cpp /
FileBackupAgent.actor.cpp:941 (version-targeted restore into a running
database, ask 10).
"""

import pytest

from foundationdb_tpu.backup import BackupAgent, RestoreAgent
from foundationdb_tpu.backup.container import BlobStoreBackupContainer
from foundationdb_tpu.net.http import BlobStoreServer, HTTPConnection
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.types import MutationType


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def _user_rows(rows):
    return [(k, v) for k, v in rows if not k.startswith(b"\xff")]


async def read_all(db):
    async def rd(tr):
        return await tr.get_range(b"", b"\xff", limit=100_000)
    return _user_rows(await db.transact(rd, max_retries=500))


def test_blobstore_http_protocol():
    """The HTTP client + object-store server speak the S3-ish subset:
    put/get round trip, integrity header, 404, prefix listing."""
    srv = BlobStoreServer()
    try:
        conn = HTTPConnection(srv.host, srv.port)
        st, _h, _b = conn.request("PUT", "/b/x%20y", {"x-crc32c": "0"},
                                  b"payload")
        assert st == 200
        st, h, body = conn.request("GET", "/b/x%20y")
        assert st == 200 and body == b"payload" and "x-crc32c" in h
        st, _h, body = conn.request("GET", "/b/missing")
        assert st == 404
        conn.request("PUT", "/b/log-1", {}, b"a")
        conn.request("PUT", "/b/log-2", {}, b"b")
        st, _h, body = conn.request("GET", "/b?prefix=log-")
        assert st == 200 and body == b"log-1\nlog-2"
        st, _h, _b = conn.request("DELETE", "/b/log-1")
        st, _h, body = conn.request("GET", "/b?prefix=log-")
        assert body == b"log-2"
    finally:
        srv.close()


def test_backup_to_blobstore_and_pit_restore_into_live_cluster():
    """Full arc: back up THROUGH the blob store under write load, then
    restore into a LIVE cluster (dirty data present) at a point-in-time
    target — the result equals the source exactly at that version; a
    full-version restore equals the final source state; a target below the
    restorable window is rejected."""
    srv = BlobStoreServer()
    src = SimCluster(seed=21, n_proxies=2, n_storage=2)
    db = src.database()
    container = BlobStoreBackupContainer(srv.url)

    async def t():
        async def seed(tr):
            for i in range(60):
                tr.set(b"pre/%03d" % i, b"v%d" % i)
        await db.transact(seed, max_retries=200)

        agent = BackupAgent(db, container, chunks=4)
        await agent.start()
        await agent.run_agent()  # snapshot chunks -> blob store
        tailer = src.loop.spawn(agent.run_log_tailer(), name="tailer")

        # phase A: writes that belong to the PIT image
        async def phase_a(tr):
            for i in range(20):
                tr.set(b"live/a%03d" % i, b"A%d" % i)
            tr.clear_range(b"pre/000", b"pre/005")
            tr.atomic_op(MutationType.ADD_VALUE, b"ctr",
                         (3).to_bytes(8, "little"))
        await db.transact(phase_a, max_retries=200)
        marker_tr = [None]

        async def marker(tr):
            marker_tr[0] = tr
            tr.set(b"\xff/pit-fence", b"x")
        await db.transact(marker, max_retries=200)
        t_a = marker_tr[0].committed_version
        expected_a = await read_all(db)

        # phase B: writes BEYOND the PIT target
        async def phase_b(tr):
            for i in range(10):
                tr.set(b"live/b%03d" % i, b"B%d" % i)
            tr.clear_range(b"live/a000", b"live/a003")
            tr.atomic_op(MutationType.ADD_VALUE, b"ctr",
                         (9).to_bytes(8, "little"))
        await db.transact(phase_b, max_retries=200)
        await agent.stop()
        await tailer
        expected_full = await read_all(db)
        assert expected_full != expected_a

        # destination: a LIVE cluster with pre-existing junk everywhere
        dst = SimCluster(seed=22, n_storage=2, loop=src.loop, net=src.net,
                         name_prefix="dst-")
        ddb = dst.database("clientD:0")

        async def dirty(tr):
            for i in range(30):
                tr.set(b"pre/%03d" % i, b"JUNK")
                tr.set(b"live/a%03d" % i, b"JUNK")
            tr.set(b"ctr", b"JUNK8byte")
        await ddb.transact(dirty, max_retries=200)

        # point-in-time restore at t_a
        restore = RestoreAgent(ddb, container)
        await restore.restore(target_version=t_a)
        got = await read_all(ddb)
        assert got == expected_a, \
            (f"PIT restore diverges: {len(got)} vs {len(expected_a)} rows; "
             f"diff {set(got) ^ set(expected_a)}")

        # full restore over the SAME live cluster reaches the final state
        await restore.restore()
        assert await read_all(ddb) == expected_full

        # a target below the restorable window is rejected loudly
        with pytest.raises(FDBError) as ei:
            await restore.restore(target_version=1)
        assert ei.value.name == "restore_invalid_version"

    src.run(src.loop.spawn(t()), max_time=600_000.0)
    srv.close()
