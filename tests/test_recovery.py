"""Recovery tests: the transaction subsystem is disposable.

Reference: SURVEY §3.3 — fdbserver/masterserver.actor.cpp masterCore (:1160)
recovery states, TagPartitionedLogSystem epochEnd (:398-417),
ClusterController recruitment, LeaderElection. The cluster here is built the
real way (RecoverableCluster): coordinators, an ELECTED cluster controller,
worker recruitment, coordinated-state writes — then roles are killed
mid-workload and the cluster must recover with invariants intact.
"""

import pytest

from foundationdb_tpu.core.future import all_of
from foundationdb_tpu.core.sim import KillType
from foundationdb_tpu.server.cluster import RecoverableCluster
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def make_cluster(**kw):
    kw.setdefault("seed", 3)
    return RecoverableCluster(**kw)


N = 5


def key(i):
    return b"cycle/%02d" % i


async def setup_ring(tr):
    for i in range(N):
        tr.set(key(i), b"%02d" % ((i + 1) % N))


def make_rotate(c, db):
    async def rotate(tr):
        r = c.rng.randint(0, N - 1)
        a = key(r)
        b_idx = int(await tr.get(a))
        b = key(b_idx)
        c_idx = int(await tr.get(b))
        ck = key(c_idx)
        d_idx = int(await tr.get(ck))
        tr.set(a, b"%02d" % c_idx)
        tr.set(b, b"%02d" % d_idx)
        tr.set(ck, b"%02d" % b_idx)
    return rotate


async def check_ring(db):
    async def read_ring(tr):
        seen = set()
        i = 0
        for _ in range(N):
            seen.add(i)
            i = int(await tr.get(key(i)))
        return i, seen
    i, seen = await db.transact(read_ring)
    assert i == 0 and len(seen) == N, f"ring broken: {seen}"


def test_boot_via_election_and_recovery():
    """Gen-1 recovery from an empty coordinated state: election, recruitment,
    cstate write, then a working transaction pipeline."""
    c = make_cluster()
    db = c.database()

    async def t():
        await db.refresh()
        await db.transact(setup_ring)
        await check_ring(db)
        cc = c.current_cc()
        assert cc is not None
        assert cc.dbinfo.epoch == 1
        assert len(cc.dbinfo.proxies) == 2

    c.run(c.loop.spawn(t()), max_time=10_000.0)


def _run_workload_with_kill(c, db, get_victim, n_rotations=16,
                            expect_new_epoch=True):
    rotate = make_rotate(c, db)
    state = {"done": 0}

    async def rotations():
        for _ in range(n_rotations):
            await db.transact(rotate, max_retries=500)
            state["done"] += 1

    async def killer():
        # let some traffic through, then kill mid-workload
        while state["done"] < 3:
            await c.loop.delay(0.1)
        victim = get_victim()
        assert victim is not None
        c.net.kill(victim)

    async def t():
        await db.refresh()
        epoch0 = c.current_cc().dbinfo.epoch
        await db.transact(setup_ring)
        await all_of([c.loop.spawn(rotations(), name="rotations"),
                      c.loop.spawn(killer(), name="killer")])
        await check_ring(db)
        if expect_new_epoch:
            # the CC is off the data path: the workload can finish while a
            # freshly elected CC is still mid-recovery — wait for it
            for _ in range(200):
                cc = c.current_cc()
                if cc is not None and cc.dbinfo.epoch > epoch0:
                    break
                await c.loop.delay(0.5)
            cc = c.current_cc()
            assert cc is not None and cc.dbinfo.epoch > epoch0, \
                "no recovery happened"

    c.run(c.loop.spawn(t()), max_time=60_000.0)
    assert state["done"] == n_rotations


def test_kill_master_mid_workload_recovers():
    c = make_cluster(seed=11)
    db = c.database()
    _run_workload_with_kill(c, db, lambda: c.current_cc().dbinfo.master)


def test_kill_tlog_mid_workload_recovers():
    c = make_cluster(seed=12)
    db = c.database()
    _run_workload_with_kill(
        c, db, lambda: c.current_cc().dbinfo.log_epochs[-1].addrs[0])


def test_kill_proxy_mid_workload_recovers():
    c = make_cluster(seed=13)
    db = c.database()
    _run_workload_with_kill(c, db, lambda: c.current_cc().dbinfo.proxies[0])


def test_kill_cluster_controller_reelects():
    """Killing the elected CC forces a re-election; the new CC re-runs
    recovery (a fresh epoch) and the cluster keeps serving."""
    c = make_cluster(seed=14)
    db = c.database()

    def cc_addr():
        cc = c.current_cc()
        return cc.process.address if cc else None

    _run_workload_with_kill(c, db, cc_addr)


def test_storage_reboot_rejoins_cluster():
    """A rebooted storage worker restores its role from durable files and
    re-binds to the current log system through the CC's DBInfo."""
    c = make_cluster(seed=15)
    db = c.database()

    async def t():
        await db.refresh()
        await db.transact(setup_ring)
        await check_ring(db)
        storages = c.current_cc().dbinfo.storages
        c.net.kill(storages[0][0], KillType.RebootProcess)
        await check_ring(db)  # reads retry through recovery + rejoin

    c.run(c.loop.spawn(t()), max_time=30_000.0)
