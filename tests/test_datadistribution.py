"""Data distribution: shard split on size + online move with fetchKeys.

Reference: fdbserver/DataDistributionTracker.actor.cpp (shardSplitter :314),
DataDistributionQueue.actor.cpp (relocator :849), MoveKeys.actor.cpp
(transactional handoff), storageserver.actor.cpp:1775 (fetchKeys).
"""

import pytest

from foundationdb_tpu.server.cluster import RecoverableCluster
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def test_oversized_shard_splits_and_moves_under_load():
    """Fill one shard far past the split threshold while a workload keeps
    writing; the tracker must split it at the sampled median and relocate
    the new shard to the least-loaded team, with every key still readable
    (including writes racing the move)."""
    KNOBS.set("DD_SHARD_SPLIT_BYTES", 4_000)
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    # one shard, two single-replica teams possible? Start with TWO shards on
    # TWO teams so the relocator has a destination; shard 0 gets the load.
    c = RecoverableCluster(seed=91, n_workers=4, n_proxies=2, n_tlogs=2,
                           n_storage=2, n_replicas=1)
    db = c.database()
    state = {"writing": True, "extra": 0}

    async def background_writer():
        # keeps writing into the HOT half of shard 0 while the move runs
        i = 0
        while state["writing"]:
            async def w(tr, i=i):
                tr.set(b"\x30hot/%04d" % i, b"x" * 40)
            await db.transact(w, max_retries=500)
            state["extra"] += 1
            i += 1
            await c.loop.delay(0.05)

    async def t():
        await db.refresh()
        info0 = c.current_cc().dbinfo
        assert len(info0.shard_boundaries) == 2
        writer = c.loop.spawn(background_writer(), name="bgWriter")

        # blast shard 0 ([b'', 0x80)) with ~10x the split threshold
        for batch in range(10):
            async def fill(tr, batch=batch):
                for j in range(20):
                    tr.set(b"\x10k%02d-%02d" % (batch, j), b"y" * 180)
            await db.transact(fill, max_retries=500)

        # wait for the tracker to split + relocate
        for _ in range(120):
            info = c.current_cc().dbinfo
            if len(info.shard_boundaries) > 2:
                break
            await c.loop.delay(0.5)
        info = c.current_cc().dbinfo
        assert len(info.shard_boundaries) > 2, "no split happened"
        teams = info.teams()
        assert len(set(map(tuple, teams))) >= 2
        # the new shard landed on a DIFFERENT team than its left neighbour
        # (the least-loaded policy had two teams serving 1 and 2 shards)
        moved = any(tuple(teams[j]) != tuple(teams[j + 1])
                    for j in range(len(teams) - 1))
        assert moved, f"split happened but nothing moved: {teams}"

        state["writing"] = False
        await writer

        # every key written — before, during, and after the move — readable
        async def read_all(tr):
            return await tr.get_range(b"", b"\xff")
        rows = await db.transact(read_all, max_retries=500)
        keys = {k for k, _v in rows}
        for batch in range(10):
            for j in range(20):
                assert b"\x10k%02d-%02d" % (batch, j) in keys, \
                    f"bulk key lost: {batch},{j}"
        hot = [k for k in keys if k.startswith(b"\x30hot/")]
        assert len(hot) == state["extra"], \
            f"racing writes lost: {len(hot)} != {state['extra']}"

    c.run(c.loop.spawn(t()), max_time=120_000.0)


def test_split_survives_recovery():
    """A post-split layout must survive a master kill: the next recovery
    reads the updated cstate (boundaries + teams), not the seed layout."""
    KNOBS.set("DD_SHARD_SPLIT_BYTES", 4_000)
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    c = RecoverableCluster(seed=92, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=2, n_replicas=1)
    db = c.database()

    async def t():
        await db.refresh()
        async def fill(tr):
            for j in range(60):
                tr.set(b"\x10s%03d" % j, b"z" * 150)
        await db.transact(fill, max_retries=300)
        for _ in range(120):
            info = c.current_cc().dbinfo
            if len(info.shard_boundaries) > 2:
                break
            await c.loop.delay(0.5)
        info = c.current_cc().dbinfo
        assert len(info.shard_boundaries) > 2, "no split happened"
        n_before = len(info.shard_boundaries)
        epoch0 = info.epoch

        c.net.kill(info.master)
        for _ in range(200):
            cc = c.current_cc()
            if cc is not None and cc.dbinfo.epoch > epoch0:
                break
            await c.loop.delay(0.5)
        cc = c.current_cc()
        assert cc is not None and cc.dbinfo.epoch > epoch0
        assert len(cc.dbinfo.shard_boundaries) == n_before, \
            "recovery lost the split layout"

        async def read_all(tr):
            return await tr.get_range(b"\x10", b"\x11")
        rows = await db.transact(read_all, max_retries=500)
        assert len(rows) == 60

    c.run(c.loop.spawn(t()), max_time=120_000.0)


def test_small_adjacent_shards_merge_back():
    """After the load that forced a split is cleared, two small adjacent
    same-team shards merge back (shardMerger :379) — metadata only, data
    intact."""
    KNOBS.set("DD_SHARD_SPLIT_BYTES", 4_000)
    KNOBS.set("DD_SHARD_MERGE_BYTES", 2_000)
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    c = RecoverableCluster(seed=93, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=1, n_replicas=1)
    db = c.database()

    async def t():
        await db.refresh()
        async def fill(tr):
            for j in range(60):
                tr.set(b"m%03d" % j, b"z" * 150)
        await db.transact(fill, max_retries=300)
        for _ in range(120):
            if len(c.current_cc().dbinfo.shard_boundaries) > 1:
                break
            await c.loop.delay(0.5)
        n_split = len(c.current_cc().dbinfo.shard_boundaries)
        assert n_split > 1, "no split happened"

        # clear the bulk: both halves now tiny and on the same team
        async def clear(tr):
            tr.clear_range(b"m", b"n")
        await db.transact(clear, max_retries=300)
        async def keep(tr):
            tr.set(b"keeper", b"1")
        await db.transact(keep, max_retries=300)
        for _ in range(240):
            if len(c.current_cc().dbinfo.shard_boundaries) < n_split:
                break
            await c.loop.delay(0.5)
        assert len(c.current_cc().dbinfo.shard_boundaries) < n_split, \
            "no merge happened"
        async def read(tr):
            return await tr.get(b"keeper")
        assert await db.transact(read, max_retries=300) == b"1"

    c.run(c.loop.spawn(t()), max_time=240_000.0)


def test_merge_after_move_coalesces_storage_ranges():
    """Regression: a team that acquired shards through MOVES holds explicit
    per-shard ranges; merges must also coalesce the storage servers' served
    ranges, or range reads spanning former boundaries get
    wrong_shard_server forever."""
    KNOBS.set("DD_SHARD_SPLIT_BYTES", 4_000)
    KNOBS.set("DD_SHARD_MERGE_BYTES", 2_000)
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    c = RecoverableCluster(seed=94, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=2, n_replicas=1)
    db = c.database()

    async def t():
        await db.refresh()
        # fill until MULTIPLE splits happened: with two teams, the second
        # split of a team already serving two shards must MOVE (least-loaded
        # policy), leaving explicit multi-entry storage ranges around
        async def fill(tr):
            for j in range(40):
                tr.set(b"\x10f%03d" % j, b"z" * 150)
        async def fill2(tr):
            for j in range(40, 80):
                tr.set(b"\x10f%03d" % j, b"z" * 150)
        await db.transact(fill, max_retries=300)
        await db.transact(fill2, max_retries=300)
        moved = False
        for _ in range(200):
            info = c.current_cc().dbinfo
            teams = [tuple(t) for t in info.teams()]
            moved = any(teams[j] == teams[j + 1] for j in
                        range(len(teams) - 1)) and len(set(teams)) > 1 \
                and len(teams) >= 4
            if moved:
                break
            await c.loop.delay(0.5)
        assert len(c.current_cc().dbinfo.shard_boundaries) >= 3, "no splits"

        # clear the bulk so adjacent same-team shards merge back
        async def clear(tr):
            tr.clear_range(b"\x10", b"\x11")
        await db.transact(clear, max_retries=300)
        async def keep(tr):
            for kb in (b"\x20a", b"\x55b", b"\x81c", b"\xc0d"):
                tr.set(kb, b"v")
        await db.transact(keep, max_retries=300)
        n_now = len(c.current_cc().dbinfo.shard_boundaries)
        for _ in range(240):
            if len(c.current_cc().dbinfo.shard_boundaries) < n_now:
                break
            await c.loop.delay(0.5)
        assert len(c.current_cc().dbinfo.shard_boundaries) < n_now, \
            "no merge happened"
        await c.loop.delay(5.0)  # let further merges settle

        # spanning reads across every former boundary must succeed
        async def span(tr):
            return await tr.get_range(b"\x11", b"\xff")
        rows = await db.transact(span, max_retries=100)
        assert {k for k, _v in rows} >= {b"\x20a", b"\x55b", b"\x81c",
                                         b"\xc0d"}, rows

    c.run(c.loop.spawn(t()), max_time=240_000.0)
