"""devlint: the DEV rule family's own tests + tier-1 enforcement.

Mirrors test_flowlint.py's three layers:
  1. Per-rule good/bad snippet fixtures for DEV001..DEV008.
  2. Regressions against the PRE-fix shapes of the real violations this PR
     fixed (sharded rebalance re-trace + raw transfers, the vmap-per-rebase
     loop, the eager un-donated rebase, profile_kernel's raw device_put) —
     the linter must catch each one as it was actually written.
  3. Enforcement: BOTH families over the full default target set must be
     clean against the committed baseline.

The interprocedural layer gets its own tests: a coroutine calling a
blocking helper (directly and through indirection) must be flagged at the
call site, and the union-of-candidates rule for duck attribute calls must
keep mixed-candidate call sites quiet.
"""

from __future__ import annotations

import textwrap

from foundationdb_tpu.analysis import flowlint
from foundationdb_tpu.analysis.__main__ import main as flowlint_main

SERVER_PATH = "foundationdb_tpu/server/snippet.py"
OPS_PATH = "foundationdb_tpu/ops/snippet.py"
SCRIPT_PATH = "scripts/snippet.py"


def lint(source: str, path: str = OPS_PATH):
    """Run only the dev family so flow findings can't muddy assertions."""
    return flowlint.analyze_source(textwrap.dedent(source), path,
                                   flowlint.active_rules("dev"))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- DEV001

def test_dev001_flags_direct_readback_in_sim_coroutine():
    findings = lint("""
        import jax
        import jax.numpy as jnp

        class Resolver:
            async def drain(self):
                await self.step()
                jax.block_until_ready(self.state)
                x = jnp.sum(self.counts)
                return float(x)
    """, SERVER_PATH)
    assert [f.rule for f in findings] == ["DEV001", "DEV001"]
    assert {f.detail for f in findings} == {"block_until_ready", "float"}
    assert all(f.symbol == "Resolver.drain" for f in findings)


def test_dev001_quiet_when_offloaded_via_run_blocking():
    findings = lint("""
        import jax

        class Resolver:
            async def drain(self, handles):
                await self.loop.run_blocking(
                    lambda hs=handles: jax.block_until_ready(hs))
    """, SERVER_PATH)
    assert findings == []


def test_dev001_float_on_untainted_host_value_is_quiet():
    findings = lint("""
        import numpy as np

        class Role:
            async def grv(self, reply):
                await self.step()
                reply.send(float(self.version) + np.asarray(reply.data).sum())
    """, SERVER_PATH)
    assert findings == []


def test_dev001_sync_and_non_sim_functions_are_quiet():
    src = """
        import jax

        class Engine:
            def warmup(self):
                jax.block_until_ready(self.state)   # sync: caller's problem
    """
    assert lint(src, SERVER_PATH) == []
    async_src = """
        import jax

        class Tool:
            async def run(self):
                await self.step()
                jax.block_until_ready(self.state)
    """
    # same readback outside a sim-visible subpackage is not flagged
    assert lint(async_src, "foundationdb_tpu/layers/snippet.py") == []
    assert rules_of(lint(async_src, SERVER_PATH)) == ["DEV001"]


def test_dev001_interprocedural_one_hop():
    """The tentpole acceptance shape: the blocking primitive lives in a
    helper, the coroutine only calls the helper — flagged AT THE CALL
    SITE, attributed to the coroutine."""
    findings = lint("""
        def materialize(state):
            state.block_until_ready()
            return state

        class Resolver:
            async def drain(self):
                await self.step()
                return materialize(self.state)
    """, SERVER_PATH)
    assert [f.rule for f in findings] == ["DEV001"]
    assert findings[0].symbol == "Resolver.drain"
    assert findings[0].detail == "materialize"
    assert "transitively" in findings[0].message


def test_dev001_interprocedural_two_hops():
    findings = lint("""
        def inner(state):
            state.block_until_ready()
            return state

        def outer(state):
            return inner(state)

        class Resolver:
            async def drain(self):
                await self.step()
                return outer(self.state)
    """, SERVER_PATH)
    assert [(f.symbol, f.detail) for f in findings] == [
        ("Resolver.drain", "outer")]


def test_dev001_interprocedural_offload_is_quiet():
    findings = lint("""
        def materialize(state):
            state.block_until_ready()
            return state

        class Resolver:
            async def drain(self):
                return await self.loop.run_blocking(
                    lambda: materialize(self.state))
    """, SERVER_PATH)
    assert findings == []


def test_dev001_duck_call_needs_all_candidates_blocking():
    """obj.sync() where only ONE same-named method blocks stays quiet —
    the conservative union rule (protects cs.detect() when the oracle
    backend is host-only)."""
    findings = lint("""
        class DeviceEngine:
            def settle(self):
                self.s.block_until_ready()
                return self.s

        class OracleEngine:
            def settle(self):
                return list(self.s)

        class Resolver:
            async def drain(self, engine):
                await self.step()
                return engine.settle()
    """, SERVER_PATH)
    assert findings == []


# ---------------------------------------------------------------- DEV002

def test_dev002_flags_immediately_invoked_jit_and_vmap():
    findings = lint("""
        import jax

        def rebuild(table_fn, bval):
            return jax.jit(jax.vmap(table_fn))(bval)
    """)
    assert [f.rule for f in findings] == ["DEV002"]
    assert findings[0].detail == "jax.jit"


def test_dev002_flags_trace_ctor_inside_loop():
    findings = lint("""
        import jax

        def rebase_all(states, fn):
            out = []
            for st in states:
                stepper = jax.vmap(fn)
                out.append(stepper(st))
            return out
    """)
    assert [f.rule for f in findings] == ["DEV002"]
    assert findings[0].detail == "jax.vmap"


def test_dev002_quiet_for_decorators_and_cached_factories():
    findings = lint("""
        import functools

        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            return jnp.maximum(x, 0)

        @functools.lru_cache(maxsize=1)
        def compiled_rebase(fn):
            return jax.jit(jax.vmap(fn), donate_argnums=(0,))

        def use(states, fn):
            return [compiled_rebase(fn)(st) for st in states]
    """)
    assert findings == []


# ---------------------------------------------------------------- DEV003

def test_dev003_flags_python_branch_on_traced_param():
    findings = lint("""
        import jax

        @jax.jit
        def step(state, flag):
            if flag:
                return state + 1
            return state
    """)
    assert [f.rule for f in findings] == ["DEV003"]
    assert findings[0].detail == "flag"
    assert findings[0].symbol == "step"


def test_dev003_flags_while_in_jit_bound_name():
    findings = lint("""
        import jax

        def countdown(state, n):
            while n:
                state, n = state + 1, n - 1
            return state

        compiled = jax.jit(countdown)
    """)
    assert [f.rule for f in findings] == ["DEV003"]
    assert findings[0].detail == "n"


def test_dev003_static_and_kwonly_params_are_quiet():
    findings = lint("""
        import functools

        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def step(state, mode):
            if mode:
                return state + 1
            return state

        def step2(state, batch, *, ablate="", intra_mode="scan"):
            if ablate in ("no_table",):
                return state
            if intra_mode == "scan":
                return batch
            return state

        compiled2 = jax.jit(step2)
    """)
    assert findings == []


def test_dev003_sees_through_shard_map():
    """`shard_map` is bound by assignment (version-gated import), not by
    a resolvable dotted path — the rule special-cases the bare name."""
    findings = lint("""
        def local_step(state, batch):
            if state:
                return batch
            return state

        def build(mesh, shard_map):
            return shard_map(local_step, mesh=mesh)
    """, "foundationdb_tpu/parallel/snippet.py")
    assert [f.rule for f in findings] == ["DEV003"]
    assert findings[0].detail == "state"


# ---------------------------------------------------------------- DEV004

def test_dev004_flags_non_constant_static_argnums():
    findings = lint("""
        import jax

        def make(fn, which):
            return jax.jit(fn, static_argnums=which)
    """)
    assert [f.rule for f in findings] == ["DEV004"]
    assert findings[0].detail == "static_argnums"


def test_dev004_flags_unhashable_value_at_static_position():
    findings = lint("""
        import jax

        def f(shapes, x):
            return x

        g = jax.jit(f, static_argnums=(0,))

        def run(x):
            return g([4, 8], x)
    """)
    assert [f.rule for f in findings] == ["DEV004"]
    assert findings[0].symbol == "run"


def test_dev004_quiet_for_constant_tuples_and_hashable_call_sites():
    findings = lint("""
        import jax

        def f(shapes, x):
            return x

        g = jax.jit(f, static_argnums=(0,))

        def run(shapes, x):
            return g(shapes, x)
    """)
    assert findings == []


# ---------------------------------------------------------------- DEV005

def test_dev005_flags_shape_dependent_ctor_outside_trace():
    findings = lint("""
        import jax.numpy as jnp

        def pack(vals):
            n = len(vals)
            return jnp.zeros((n, 4))
    """)
    assert [f.rule for f in findings] == ["DEV005"]
    assert findings[0].symbol == "pack"


def test_dev005_quiet_inside_trace_reachable_helpers():
    """A helper only called from a jitted function runs traced: its
    shape-derived sizes are static by construction (the _build_table
    shape, reached from conflict_step)."""
    findings = lint("""
        import jax
        import jax.numpy as jnp

        def build_table(vals):
            k = vals.shape[0]
            return jnp.zeros((k, k))

        @jax.jit
        def step(state):
            return build_table(state)
    """)
    assert findings == []


def test_dev005_quiet_for_static_sizes():
    findings = lint("""
        import jax.numpy as jnp

        CAP = 4096

        def fresh():
            return jnp.zeros((CAP, 4))
    """)
    assert findings == []


# ---------------------------------------------------------------- DEV006

def test_dev006_flags_overwrite_through_undonated_jit_name():
    findings = lint("""
        import jax

        def rebase(state, delta):
            return state

        compiled = jax.jit(rebase)

        class Engine:
            def tick(self, delta):
                self._state = compiled(self._state, delta)
    """)
    assert [f.rule for f in findings] == ["DEV006"]
    assert findings[0].detail == "compiled"


def test_dev006_flags_undonated_factory_and_donated_factory_is_quiet():
    bad = lint("""
        import functools

        import jax

        def rebase(state, delta):
            return state

        @functools.lru_cache(maxsize=1)
        def compiled_rebase():
            return jax.jit(rebase)

        class Engine:
            def tick(self, delta):
                self._state = compiled_rebase()(self._state, delta)
    """)
    assert [f.rule for f in bad] == ["DEV006"]
    good = lint("""
        import functools

        import jax

        def rebase(state, delta):
            return state

        @functools.lru_cache(maxsize=1)
        def compiled_rebase():
            return jax.jit(rebase, donate_argnums=(0,))

        class Engine:
            def tick(self, delta):
                self._state = compiled_rebase()(self._state, delta)
    """)
    assert good == []


def test_dev006_quiet_when_result_does_not_overwrite_operand():
    findings = lint("""
        import jax

        def rebase(state, delta):
            return state

        compiled = jax.jit(rebase)

        class Engine:
            def peek(self, delta):
                preview = compiled(self._state, delta)
                return preview
    """)
    assert findings == []


# ---------------------------------------------------------------- DEV007

def test_dev007_flags_raw_transfers_outside_jaxenv():
    findings = lint("""
        import jax

        def upload(batch, sharding):
            dev = jax.device_put(batch, sharding)
            return jax.device_get(dev)
    """)
    assert [f.rule for f in findings] == ["DEV007", "DEV007"]
    assert {f.detail for f in findings} == {
        "jax.device_put", "jax.device_get"}


def test_dev007_jaxenv_module_itself_is_sanctioned():
    findings = lint("""
        import jax

        def device_put(x):
            return jax.device_put(x)
    """, "foundationdb_tpu/utils/jaxenv.py")
    assert findings == []


def test_dev007_choke_point_callers_are_quiet():
    findings = lint("""
        from foundationdb_tpu.utils import jaxenv

        def upload(batch):
            return jaxenv.device_put(batch)
    """)
    assert findings == []


# ---------------------------------------------------------------- DEV008

def test_dev008_flags_module_global_numpy_prng():
    findings = lint("""
        import numpy as np

        def jitter(n):
            np.random.seed(0)
            return np.random.randn(n)
    """)
    assert [f.rule for f in findings] == ["DEV008", "DEV008"]
    assert {f.detail for f in findings} == {
        "numpy.random.seed", "numpy.random.randn"}


def test_dev008_seeded_instances_are_quiet():
    findings = lint("""
        import numpy as np

        def jitter(n, seed):
            rng = np.random.RandomState(seed)
            return rng.randn(n) + np.random.default_rng(seed).random()
    """)
    assert findings == []


def test_dev008_flags_jax_key_reuse_without_split():
    findings = lint("""
        import jax

        def sample(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
    """)
    assert [f.rule for f in findings] == ["DEV008"]
    assert findings[0].detail == "key:key"


def test_dev008_split_rotation_is_quiet():
    findings = lint("""
        import jax

        def sample(key):
            key, sub = jax.random.split(key)
            a = jax.random.normal(sub, (4,))
            key, sub = jax.random.split(key)
            b = jax.random.uniform(sub, (4,))
            return a + b
    """)
    assert findings == []


# ----------------------------------------- PRE-fix shapes of real bugs

def test_prefix_sharded_rebalance_retrace_and_raw_transfers():
    """parallel/sharded_conflict.py rebalance_cuts, as committed before
    this PR: raw device_get/device_put transfers plus an inline
    jax.jit(jax.vmap(...))(...) — a re-trace AND re-compile per partition
    move."""
    findings = lint("""
        import jax
        import numpy as np

        class ShardedDeviceConflictSet:
            def rebalance_cuts(self, new_cut_bytes, at_version):
                st = jax.device_get(self._state)
                new_bval = np.zeros_like(st["bval"])
                bval_dev = jax.device_put(new_bval, self._sharding)
                self._state = {
                    "bval": bval_dev,
                    "table": jax.jit(jax.vmap(self._build_table))(bval_dev),
                }
    """, "foundationdb_tpu/parallel/snippet.py")
    assert rules_of(findings) == ["DEV002", "DEV007"]
    assert sum(f.rule == "DEV007" for f in findings) == 2


def test_prefix_sharded_vmap_rebase_in_loop():
    """parallel/sharded_conflict.py _maybe_rebase, pre-fix: a fresh
    jax.vmap closure built and invoked inside the rebase while-loop."""
    findings = lint("""
        import jax

        from foundationdb_tpu.ops.conflict import rebase_state

        class ShardedDeviceConflictSet:
            def _maybe_rebase(self, commit_version):
                while commit_version - self.base > self.threshold:
                    delta = min(commit_version - self.base, 1 << 30)
                    core = jax.vmap(lambda s: rebase_state(s, delta))(
                        self._core)
                    self._core = core
                    self.base += delta
    """, "foundationdb_tpu/parallel/snippet.py")
    assert rules_of(findings) == ["DEV002"]


def test_prefix_eager_undonated_rebase():
    """ops/conflict.py DeviceConflictSet._maybe_rebase, pre-fix: the state
    overwritten by an EAGER rebase_state call — op-by-op dispatch, dead
    input buffers alive alongside the new state."""
    findings = lint("""
        import jax.numpy as jnp

        def rebase_state(state, delta):
            return {"bval": jnp.maximum(state["bval"] - delta, -5)}

        class DeviceConflictSet:
            def _maybe_rebase(self, commit_version):
                while commit_version - self.base > self.threshold:
                    delta = min(commit_version - self.base, 1 << 30)
                    self._state = rebase_state(self._state, delta)
                    self.base += delta
    """)
    assert rules_of(findings) == ["DEV006"]
    assert findings[0].detail == "rebase_state"


def test_prefix_profile_kernel_raw_device_put():
    """scripts/profile_kernel.py, pre-fix: raw jax.device_put for the
    batch upload instead of the jaxenv choke point."""
    findings = lint("""
        import jax

        def main(warm_np, main_np):
            warm = jax.device_put(warm_np)
            stacked = jax.device_put(main_np)
            return warm, stacked
    """, SCRIPT_PATH)
    assert [f.rule for f in findings] == ["DEV007", "DEV007"]


# ------------------------------------------------------------- suppression

def test_devlint_inline_suppression_tag():
    findings = lint("""
        import jax

        class Resolver:
            async def drain(self):
                await self.step()
                jax.block_until_ready(self.s)  # devlint: ignore[DEV001]
    """, SERVER_PATH)
    assert findings == []


# ---------------------------------------------------------- output / CLI

def test_github_format_escapes_and_annotates():
    findings = lint("""
        import jax

        def upload(x):
            return jax.device_put(x)
    """)
    out = flowlint.format_github(findings)
    assert out.startswith("::error file=foundationdb_tpu/ops/snippet.py,")
    assert ",line=5,title=DEV007 [upload]::" in out
    assert "\n" not in out  # single finding -> single annotation line


def test_cli_family_flag_selects_rule_set(capsys):
    assert flowlint_main(["--family", "dev", "--list-rules"]) == 0
    codes = [line.split()[0] for line in
             capsys.readouterr().out.strip().splitlines()]
    assert codes and all(c.startswith("DEV") for c in codes)
    assert flowlint_main(["--family", "flow", "--list-rules"]) == 0
    codes = [line.split()[0] for line in
             capsys.readouterr().out.strip().splitlines()]
    assert codes and all(c.startswith("FLOW") for c in codes)


def test_family_scoped_baseline_runs_ignore_other_family(tmp_path):
    """A flow-only run must not report the dev grandfathers stale (and
    vice versa) — the family filter in apply_baseline."""
    baseline = flowlint.Baseline(entries=[
        {"rule": "DEV007", "path": "p.py", "symbol": "f",
         "detail": "jax.device_put", "reason": "doc"}])
    new, stale = flowlint.apply_baseline([], baseline, families={"flow"})
    assert new == [] and stale == []
    new, stale = flowlint.apply_baseline([], baseline, families={"dev"})
    assert [e["rule"] for e in stale] == ["DEV007"]


# ------------------------------------------------------------- enforcement

def test_at_least_eight_dev_rules_active():
    codes = [r.code for r in flowlint.active_rules("dev")]
    assert len(codes) == len(set(codes))
    assert len(codes) >= 8


def test_package_and_scripts_clean_under_both_families():
    """THE enforcement test for this PR: BOTH rule families over the full
    default target set (package + scripts/) report zero non-baselined
    findings and zero stale entries."""
    findings = flowlint.analyze_paths(flowlint.default_targets(),
                                      flowlint.active_rules("all"))
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    new, stale = flowlint.apply_baseline(findings, baseline)
    assert new == [], "new violations:\n" + flowlint.format_text(new)
    assert stale == [], f"stale baseline entries: {stale}"


def test_dev_baseline_entries_are_documented():
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    dev = [e for e in baseline.entries if e["rule"].startswith("DEV")]
    assert dev, "expected at least one documented dev grandfather"
    for entry in dev:
        reason = entry.get("reason", "")
        assert reason and not reason.startswith("FIXME"), (
            f"undocumented baseline entry: {entry}")
