"""Native redwood read-path parity: the C RedwoodRun handle, per-run bloom
filters, and the batched GetValuesReply encoder must agree with their
pure-Python fallbacks on every decision and every byte, over randomized
flush/compact/reopen cycles including torn-run and superseded-run recovery
states.

The fuzz bodies double as the sanitized-build corpus: scripts/
native_sanitize_fuzz.py imports and re-runs them against the ASan/UBSan
instrumented extension, so every parity input here is also a memory-safety
input there. Keep this module outside the jax import closure.
"""

import pytest

from foundationdb_tpu import native
from foundationdb_tpu.storage import redwood as R
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom

HAVE_NATIVE = native.available() and hasattr(native.mod, "redwood_run_open")

pytestmark = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native module without redwood read path")


def _rand_key(rng):
    return bytes(rng.randint(97, 105) for _ in range(rng.randint(1, 10)))


def _rand_entries(rng, n):
    keys = sorted({_rand_key(rng) for _ in range(n)})
    return [(k, bytes(rng.randint(0, 255)
                      for _ in range(rng.randint(0, 24)))) for k in keys]


def _rand_clears(rng, n):
    out = []
    for _ in range(n):
        b, e = sorted((_rand_key(rng), _rand_key(rng)))
        if b != e:
            out.append((b, e))
    return out


def _ref_run_lookup(entries_map, clears, key):
    """Single-run reference decision: (status, value) with the C contract —
    1 = found (an in-run entry beats the run's own clears), 2 = shadowed,
    0 = miss."""
    if key in entries_map:
        return 1, entries_map[key]
    if any(b <= key < e for b, e in clears):
        return 2, None
    return 0, None


def _build_image(rng, entries, clears, run_id=1, bpk=10, nh=6):
    return R.build_run_image(
        entries, clears, meta={}, run_id=run_id, meta_seq=run_id,
        level=0, sources=(), block_bytes=rng.random_choice([64, 128, 512]),
        bloom_bits_per_key=bpk, bloom_hashes=nh)


# ---------------------------------------------------------------------------
# bloom filters: byte + decision parity, never-false-negative
# ---------------------------------------------------------------------------

def fuzz_bloom_parity(seed=0, rounds=60):
    rng = DeterministicRandom(seed)
    for _ in range(rounds):
        keys = [k for k, _v in _rand_entries(rng, rng.randint(0, 40))]
        bpk = rng.randint(1, 16)
        nh = rng.randint(1, 12)
        c_sec = native.mod.redwood_bloom_build(keys, bpk, nh)
        py_sec = R.py_bloom_build(keys, bpk, nh)
        assert c_sec == py_sec  # byte-identical, not just equivalent
        for k in keys:  # members: NEVER a false negative, either side
            assert native.mod.redwood_bloom_query(c_sec, k) is True
            assert R.py_bloom_query(py_sec, k) is True
        for _ in range(30):  # non-members: identical (maybe-False) verdicts
            probe = _rand_key(rng)
            assert (native.mod.redwood_bloom_query(c_sec, probe)
                    == R.py_bloom_query(py_sec, probe))


def test_bloom_parity_fuzz():
    fuzz_bloom_parity(seed=101)


def test_bloom_rejects_bad_inputs():
    for fn in (native.mod.redwood_bloom_build, R.py_bloom_build):
        with pytest.raises(ValueError):
            fn([b"k"], 0, 6)  # bits_per_key < 1
        with pytest.raises(ValueError):
            fn([b"k"], 10, 0)  # n_hashes out of range
        with pytest.raises(ValueError):
            fn([b"k"], 10, 65)
    sec = R.py_bloom_build([b"alpha", b"beta"], 10, 6)
    for bad in (b"", sec[:10], sec + b"\x00", b"\x00" * len(sec)):
        with pytest.raises(ValueError):
            native.mod.redwood_bloom_query(bad, b"alpha")
        with pytest.raises(ValueError):
            R.py_bloom_query(bad, b"alpha")


# ---------------------------------------------------------------------------
# run handle: open/get parity over randomized runs, corruption rejection
# ---------------------------------------------------------------------------

def fuzz_run_handle_parity(seed=0, rounds=40):
    rng = DeterministicRandom(seed)
    for _ in range(rounds):
        entries = _rand_entries(rng, rng.randint(0, 60))
        clears = _rand_clears(rng, rng.randint(0, 4))
        bpk = rng.random_choice([0, 10])  # with and without a bloom section
        image = _build_image(rng, entries, clears, bpk=bpk)
        handle = native.mod.redwood_run_open(
            image, [tuple(c) for c in clears], rng.randint(1, 8))
        emap = dict(entries)
        probes = [k for k, _v in entries] + [_rand_key(rng)
                                             for _ in range(80)]
        for k in probes:
            st, val = handle.get(k)
            ref_st, ref_val = _ref_run_lookup(emap, clears, k)
            assert (st, val) == (ref_st, ref_val), (k, st, ref_st)
            if bpk:  # bloom verdicts agree between C handle and Python
                bloom = R.py_bloom_build([k for k, _v in entries], bpk, 6)
                if not R.py_bloom_query(bloom, k):
                    assert st in (0, 2)  # a negative can never hide a hit
        stats = handle.stats()
        assert stats["blocks_decoded"] <= stats["block_cache_misses"] + 1
        handle.close()
        handle.close()  # idempotent
        with pytest.raises(ValueError):
            handle.get(b"x")  # closed handle refuses reads


def test_run_handle_parity_fuzz():
    fuzz_run_handle_parity(seed=202)


def fuzz_run_open_rejects_corrupt(seed=0, rounds=40):
    rng = DeterministicRandom(seed)
    entries = _rand_entries(rng, 30)
    image = _build_image(rng, entries, [])
    for _ in range(rounds):
        mode = rng.randint(0, 2)
        if mode == 0:  # truncation anywhere
            bad = image[:rng.randint(0, len(image) - 1)]
        elif mode == 1:  # body byte flip -> CRC mismatch
            i = rng.randint(R._RUN_HEADER.size, len(image) - 1)
            bad = image[:i] + bytes([image[i] ^ 0xFF]) + image[i + 1:]
        else:  # header magic/version stomp
            i = rng.randint(0, 7)
            bad = image[:i] + bytes([image[i] ^ 0xFF]) + image[i + 1:]
        with pytest.raises(ValueError):
            native.mod.redwood_run_open(bad, [], 4)
        assert R.parse_run(bad, None, "") is None  # Python agrees: unusable


def test_run_open_rejects_corrupt_images():
    fuzz_run_open_rejects_corrupt(seed=303)


def fuzz_runs_cascade_parity(seed=0, rounds=25):
    """Multi-run newest-first cascade (redwood_runs_get / get_batch) vs a
    Python fold over the same shadowing rules."""
    rng = DeterministicRandom(seed)
    for _ in range(rounds):
        runs = []  # newest first: (entries_map, clears, handle)
        for run_id in range(rng.randint(1, 4), 0, -1):
            entries = _rand_entries(rng, rng.randint(0, 40))
            clears = _rand_clears(rng, rng.randint(0, 3))
            image = _build_image(rng, entries, clears, run_id=run_id,
                                 bpk=rng.random_choice([0, 10]))
            handle = native.mod.redwood_run_open(image, clears, 4)
            runs.append((dict(entries), clears, handle))
        handles = [h for _e, _c, h in runs]

        def ref_get(key):
            for emap, clears, _h in runs:  # newest -> oldest
                st, val = _ref_run_lookup(emap, clears, key)
                if st == 1:
                    return val
                if st == 2:
                    return None
            return None

        probes = [_rand_key(rng) for _ in range(120)]
        for k in probes:
            assert native.mod.redwood_runs_get(handles, k) == ref_get(k)
        batch = native.mod.redwood_runs_get_batch(handles, probes)
        assert batch == [ref_get(k) for k in probes]
        for h in handles:
            h.close()


def test_runs_cascade_parity_fuzz():
    fuzz_runs_cascade_parity(seed=404)


# ---------------------------------------------------------------------------
# store-level lifecycle parity: native vs Python fallback vs dict model over
# flush/compact/reopen cycles, torn tails, superseded sources
# ---------------------------------------------------------------------------

def _store_knobs():
    KNOBS.set("REDWOOD_MEMTABLE_BYTES", 512)
    KNOBS.set("REDWOOD_BLOCK_BYTES", 128)
    KNOBS.set("REDWOOD_COMPACTION_FAN_IN", 2)
    KNOBS.set("REDWOOD_BLOCK_CACHE_BLOCKS", 8)


def fuzz_store_lifecycle_parity(seed=0, ops=500, kills=2):
    """One mutation stream -> a dict model, reads cross-checked with the
    native path ON and OFF after every maintenance step, through sim kills
    (torn WAL/run tails) and recovery."""
    from tests.test_redwood import _Files
    _store_knobs()
    try:
        rng = DeterministicRandom(seed)
        fs = _Files(seed)
        st = fs.store()
        model: dict[bytes, bytes] = {}
        synced: dict[bytes, bytes] = {}
        for i in range(ops):
            k = b"k%03d" % rng.randint(0, 149)
            if rng.randint(0, 9) == 0:
                b, e = sorted((b"k%03d" % rng.randint(0, 149),
                               b"k%03d" % rng.randint(0, 149)))
                st.clear_range(b, e)
                for kk in [kk for kk in model if b <= kk < e]:
                    del model[kk]
            else:
                v = b"v%05d" % i
                st.set(k, v)
                model[k] = v
            if rng.randint(0, 3) == 0:
                st.commit()
                st.maintain()
                synced = dict(model)
            if kills and rng.randint(0, ops // (kills + 1)) == 0:
                kills -= 1
                fs.kill_all()
                st = fs.store()
                st.recover()
                model = dict(synced)
        st.commit()
        st.maintain()
        probes = sorted({b"k%03d" % i for i in range(150)}
                        | {_rand_key(rng) for _ in range(50)})
        KNOBS.set("REDWOOD_NATIVE_READS", 1)
        native_reads = [st.get(k) for k in probes]
        native_batch = st.get_batch(probes)
        KNOBS.set("REDWOOD_NATIVE_READS", 0)
        py_reads = [st.get(k) for k in probes]
        expect = [model.get(k) for k in probes]
        assert native_reads == expect
        assert native_batch == expect
        assert py_reads == expect
        # reopen once more: recovery reopens native handles from disk
        st2 = fs.store()
        st2.recover()
        KNOBS.set("REDWOOD_NATIVE_READS", 1)
        assert [st2.get(k) for k in probes] == expect
    finally:
        KNOBS.reset()


def test_store_lifecycle_parity_fuzz():
    fuzz_store_lifecycle_parity(seed=505)


def test_superseded_run_recovery_retires_native_handles():
    """A crash between a compacted run's sync and its source truncation
    leaves both on disk; recovery must drop the sources (and their C
    handles) and serve only the merged run — on both read paths."""
    from tests.test_redwood import _Files
    _store_knobs()
    fs = _Files(7)
    # manufacture the state directly: two level-0 sources + the merged run
    a = R.build_run_image([(b"a", b"1"), (b"b", b"stale")], [], {},
                          run_id=1, meta_seq=1, level=0, sources=(),
                          block_bytes=128)
    b = R.build_run_image([(b"b", b"2"), (b"c", b"3")], [], {},
                          run_id=2, meta_seq=2, level=0, sources=(),
                          block_bytes=128)
    merged = R.build_run_image(
        [(b"a", b"1"), (b"b", b"2"), (b"c", b"3")], [], {},
        run_id=3, meta_seq=2, level=1, sources=(1, 2), block_bytes=128)
    for name, img in (("rw.1", a), ("rw.2", b), ("rw.3", merged)):
        f = fs.open(name)
        f.append(img)
        f.sync()
    st = fs.store()
    st.recover()
    assert st.run_names() == ["rw.3"]
    for knob in (1, 0):
        KNOBS.set("REDWOOD_NATIVE_READS", knob)
        assert st.get(b"a") == b"1"
        assert st.get(b"b") == b"2"
        assert st.get(b"c") == b"3"
        assert st.get(b"zz") is None


# ---------------------------------------------------------------------------
# batched encoded replies: byte parity with the Python wire encoder
# ---------------------------------------------------------------------------

def fuzz_batched_encode_parity(seed=0, rounds=6):
    from tests.test_redwood import _Files
    from foundationdb_tpu.server.interfaces import GetValuesReply
    from foundationdb_tpu.utils import wire
    _store_knobs()
    try:
        tid = wire.type_id(GetValuesReply)
        rng = DeterministicRandom(seed)
        for _ in range(rounds):
            fs = _Files(rng.randint(0, 1 << 30))
            st = fs.store()
            model: dict[bytes, bytes] = {}
            for i in range(rng.randint(50, 300)):
                k = b"k%03d" % rng.randint(0, 99)
                v = b"v%05d" % i
                st.set(k, v)
                model[k] = v
                if rng.randint(0, 4) == 0:
                    st.commit()
                    st.maintain()
            st.commit()
            st.maintain()
            oldest = 50
            reads = [(b"k%03d" % rng.randint(0, 120),
                      rng.randint(0, 100)) for _ in range(150)]
            enc = st.get_batch_encoded(reads, oldest, tid)
            assert enc is not None  # all runs carry native handles here
            results = [(1, "transaction_too_old") if v < oldest
                       else (0, model.get(k)) for k, v in reads]
            assert enc == wire.dumps(GetValuesReply(results=results))
    finally:
        KNOBS.reset()


def test_batched_encode_parity_fuzz():
    fuzz_batched_encode_parity(seed=606)


# ---------------------------------------------------------------------------
# acceptance: blooms measurably cut blocks decoded on cold misses
# ---------------------------------------------------------------------------

def _cold_miss_blocks(bpk, native_reads):
    from tests.test_redwood import _Files
    KNOBS.set("REDWOOD_MEMTABLE_BYTES", 512)
    KNOBS.set("REDWOOD_BLOCK_BYTES", 128)
    KNOBS.set("REDWOOD_COMPACTION_FAN_IN", 4)  # keep several runs live
    KNOBS.set("REDWOOD_BLOOM_BITS_PER_KEY", bpk)
    KNOBS.set("REDWOOD_NATIVE_READS", native_reads)
    fs = _Files(11)
    st = fs.store()
    for i in range(400):
        st.set(b"k%04dp" % i, b"v%04d" % i)
        if i % 60 == 59:
            st.commit()
            st.maintain()
    st.commit()
    st.maintain()
    st2 = fs.store()  # fresh store: every block cache is cold
    st2.recover()
    for i in range(400):
        # interleaved misses: each bisects into a different block, so
        # without a bloom every one decodes a cold block
        assert st2.get(b"k%04dx" % i) is None
    return st2.read_stats()


@pytest.mark.parametrize("native_reads", [1, 0])
def test_bloom_reduces_cold_miss_block_decodes(native_reads):
    with_bloom = _cold_miss_blocks(10, native_reads)
    without = _cold_miss_blocks(0, native_reads)
    assert with_bloom["bloom_negatives"] > 0
    assert with_bloom["blocks_decoded"] < without["blocks_decoded"]
    if native_reads:
        assert with_bloom["native_gets"] > 0
        assert with_bloom["fallback_gets"] == 0
    else:
        assert with_bloom["native_gets"] == 0
        assert with_bloom["fallback_gets"] > 0
