"""Backup/restore: transactional range snapshots + the proxies' mutation-log
tee (\\xff/blog), driven by the TaskBucket, restored into a fresh cluster.

Reference: FileBackupAgent.actor.cpp:941 (BackupRangeTaskFunc),
MasterProxyServer.actor.cpp:664-776 (log tee), TaskBucket.actor.cpp,
Restore.actor.cpp. The invariant: restore reproduces EXACTLY the source
database's user-keyspace state at the backup's end version — even though
snapshot chunks were taken at different versions mid-write-load — because
the mutation log covers every committed write in the window.
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.backup import BackupAgent, BackupContainer, RestoreAgent
from foundationdb_tpu.backup.taskbucket import TaskBucket
from foundationdb_tpu.server.cluster import RecoverableCluster, SimCluster
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield
    KNOBS.reset()


def test_taskbucket_pop_is_exclusive_and_leases_expire():
    c = SimCluster(seed=8, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1)
    db = c.database()

    async def t():
        tb = TaskBucket(db, lease_seconds=2.0)
        await tb.add({"n": 1})
        await tb.add({"n": 2})
        k1, t1 = await tb.pop()
        k2, t2 = await tb.pop()
        assert {t1["n"], t2["n"]} == {1, 2}
        assert await tb.pop() is None  # both leased
        await tb.finish(k1)
        assert not await tb.is_empty()
        # k2's lease expires -> poppable again (crash-safety)
        await c.loop.delay(2.5)
        k3, t3 = await tb.pop()
        assert t3["n"] == t2["n"]
        await tb.finish(k3)
        assert await tb.is_empty()

    c.run(c.loop.spawn(t()), max_time=600.0)


def _user_rows(rows):
    return [(k, v) for k, v in rows if not k.startswith(b"\xff")]


def test_backup_restore_roundtrip_under_write_load():
    """Take a backup WHILE writes keep landing; restore into a fresh
    cluster; the result must equal the source at the backup's end version
    exactly (rows written after stop() are absent)."""
    src = SimCluster(seed=9, n_proxies=2, n_resolvers=2, n_tlogs=1,
                     n_storage=2)
    db = src.database()
    container = BackupContainer()

    async def t():
        # phase 0: pre-existing data (will be in the snapshot chunks)
        async def seed(tr):
            for i in range(50):
                tr.set(b"pre/%03d" % i, b"v%d" % i)
        await db.transact(seed, max_retries=200)

        agent = BackupAgent(db, container, chunks=4)
        await agent.start()

        # concurrent load: overwrites, new keys, deletes, atomic adds
        state = {"stop": False}

        async def writer():
            n = 0
            from foundationdb_tpu.utils.types import MutationType
            while not state["stop"]:
                async def w(tr, n=n):
                    tr.set(b"live/%04d" % n, b"x%d" % n)
                    tr.set(b"pre/%03d" % (n % 50), b"updated%d" % n)
                    if n % 7 == 0:
                        tr.clear_range(b"live/%04d" % max(0, n - 5),
                                       b"live/%04d" % max(1, n - 4))
                    tr.atomic_op(MutationType.ADD_VALUE, b"counter",
                                 (1).to_bytes(8, "little"))
                await db.transact(w, max_retries=200)
                n += 1
                await src.loop.delay(0.05)
        wtask = src.loop.spawn(writer(), name="bgwriter")

        # two backup agents race on the TaskBucket + a log tailer
        a1 = src.loop.spawn(agent.run_agent(), name="agent1")
        a2 = src.loop.spawn(agent.run_agent(), name="agent2")
        tailer = src.loop.spawn(agent.run_log_tailer(), name="tailer")
        await a1
        await a2

        await src.loop.delay(1.0)  # more writes after the snapshot finished
        end_version = await agent.stop()
        await tailer

        # writes AFTER stop must not be in the restore
        async def late(tr):
            tr.set(b"late/after-stop", b"nope")
        await db.transact(late, max_retries=200)

        # capture source truth at end_version
        async def readall(tr):
            tr._read_version = end_version
            return await tr.get_range(b"", b"\xff")
        truth = _user_rows(await db.transact(readall, max_retries=200))

        state["stop"] = True
        await wtask
        return truth

    truth = src.run(src.loop.spawn(t()), max_time=600_000.0)

    # restore into a FRESH cluster
    dst = SimCluster(seed=10, n_proxies=1, n_resolvers=1, n_tlogs=1,
                     n_storage=2)
    db2 = dst.database()

    async def r():
        agent = RestoreAgent(db2, container)
        await agent.restore()

        async def readall(tr):
            return await tr.get_range(b"", b"\xff")
        return _user_rows(await db2.transact(readall, max_retries=200))

    got = dst.run(dst.loop.spawn(r()), max_time=600_000.0)
    assert got == truth, (
        f"restore mismatch: {len(got)} vs {len(truth)} rows; "
        f"missing={set(dict(truth)) - set(dict(got))} "
        f"extra={set(dict(got)) - set(dict(truth))}")
    assert not any(k.startswith(b"late/") for k, _v in got)


def test_backup_survives_recovery_midstream():
    """A master kill mid-backup: the tee must survive the recovery (the
    recovery transaction re-propagates backup ranges), and the restore
    still matches the source at end version."""
    from foundationdb_tpu.core.sim import KillType

    KNOBS.set("DD_INTERVAL_SECONDS", 3600.0)  # keep DD quiet for this one
    src = RecoverableCluster(seed=41, n_workers=5, n_proxies=2, n_tlogs=2,
                             n_storage=2, n_replicas=1)
    db = src.database()
    container = BackupContainer()

    async def t():
        await db.refresh()
        async def seed(tr):
            for i in range(30):
                tr.set(b"k%03d" % i, b"v%d" % i)
        await db.transact(seed, max_retries=500)

        agent = BackupAgent(db, container, chunks=2)
        await agent.start()
        a1 = src.loop.spawn(agent.run_agent(), name="agent1")
        await a1

        # recovery mid-backup
        cc = src.current_cc()
        src.net.kill(cc.dbinfo.master, KillType.RebootProcess)

        # post-recovery writes MUST be teed (the recovery txn re-propagates
        # the backup ranges to the new generation's proxies)
        for i in range(30, 60):
            async def w(tr, i=i):
                tr.set(b"k%03d" % i, b"v%d" % i)
            await db.transact(w, max_retries=500)

        tailer = src.loop.spawn(agent.run_log_tailer(), name="tailer")
        end_version = await agent.stop()
        await tailer

        async def readall(tr):
            tr._read_version = end_version
            return await tr.get_range(b"", b"\xff")
        return _user_rows(await db.transact(readall, max_retries=500))

    truth = src.run(src.loop.spawn(t()), max_time=600_000.0)
    assert len([k for k, _ in truth if k.startswith(b"k")]) == 60

    dst = SimCluster(seed=12, n_proxies=1, n_resolvers=1, n_tlogs=1,
                     n_storage=1)
    db2 = dst.database()

    async def r():
        await RestoreAgent(db2, container).restore()

        async def readall(tr):
            return await tr.get_range(b"", b"\xff")
        return _user_rows(await db2.transact(readall, max_retries=200))

    got = dst.run(dst.loop.spawn(r()), max_time=600_000.0)
    assert got == truth


def test_fdbbackup_cli_commands(tmp_path):
    """fdbbackup start/status/stop + fdbrestore over a directory container
    (backup.actor.cpp's operator surface)."""
    from foundationdb_tpu.tools import fdbbackup as B

    src = SimCluster(seed=21, n_proxies=1, n_resolvers=1, n_tlogs=1,
                     n_storage=1)
    db = src.database()
    d = str(tmp_path / "container")

    async def t():
        async def seed(tr):
            for i in range(20):
                tr.set(b"b%02d" % i, b"v%d" % i)
        await db.transact(seed, max_retries=200)
        assert "no backup" in await B.run_command(db, ["status"])
        out = await B.run_command(db, ["start", "-d", d])
        assert "snapshot complete" in out
        assert "state: active" in await B.run_command(db, ["status"])
        async def more(tr):
            tr.set(b"b99", b"late")
        await db.transact(more, max_retries=200)
        out = await B.run_command(db, ["stop", "-d", d])
        assert "restorable" in out
        assert "state: stopped" in await B.run_command(db, ["status"])
    src.run(src.loop.spawn(t()), max_time=600_000.0)

    dst = SimCluster(seed=22, n_proxies=1, n_resolvers=1, n_tlogs=1,
                     n_storage=1)
    db2 = dst.database()

    async def r():
        await B.run_command(db2, ["restore", "-d", d])
        async def readall(tr):
            return await tr.get_range(b"", b"\xff")
        return _user_rows(await db2.transact(readall, max_retries=200))
    rows = dst.run(dst.loop.spawn(r()), max_time=600_000.0)
    keys = dict(rows)
    assert keys.get(b"b99") == b"late"
    assert len([k for k in keys if k.startswith(b"b")]) == 21


def test_backup_restore_under_fault_cocktail():
    """The BackupUnderAttrition composition as a pinned spec: snapshot
    chunks + the log tee keep streaming while the source's transaction
    subsystem is clogged and killed/rebooted; the restore must still equal
    the source at end version byte-for-byte."""
    from foundationdb_tpu.core.sim import KillType
    from foundationdb_tpu.utils.errors import FDBError
    from foundationdb_tpu.utils.rng import DeterministicRandom
    from foundationdb_tpu.utils.types import MutationType

    src = RecoverableCluster(seed=43, n_workers=5, n_proxies=2, n_tlogs=2,
                             n_storage=2, n_replicas=1)
    db = src.database()
    container = BackupContainer()
    rng = DeterministicRandom(4302)

    async def t():
        await db.refresh(max_wait=120.0)

        async def seed(tr):
            for i in range(40):
                tr.set(b"fc/%03d" % i, b"v%d" % i)
        await db.transact(seed, max_retries=500)

        agent = BackupAgent(db, container, chunks=3)
        await agent.start()

        state = {"stop": False}

        async def writer():
            n = 0
            while not state["stop"]:
                async def w(tr, n=n):
                    tr.set(b"fc/live/%04d" % n, b"x%d" % n)
                    tr.set(b"fc/%03d" % (n % 40), b"u%d" % n)
                    if n % 5 == 0:
                        tr.clear_range(b"fc/live/%04d" % max(0, n - 4),
                                       b"fc/live/%04d" % max(1, n - 3))
                    tr.atomic_op(MutationType.ADD_VALUE, b"fc/ctr",
                                 (1).to_bytes(8, "little"))
                try:
                    await db.transact(w, max_retries=1000)
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                n += 1
                await src.loop.delay(0.1)
        wtask = src.loop.spawn(writer(), name="fcWriter")

        # fault cocktail against the live stream: clog random links, kill
        # (and auto-reboot) txn-subsystem workers — each kill forces a
        # recovery the backup tee must survive
        async def faults():
            workers = [p.address for p in src.worker_procs]
            everyone = workers + [p.address for p in src.storage_worker_procs]
            for _ in range(6):
                await src.loop.delay(1.5 + rng.random())
                a = everyone[rng.randint(0, len(everyone) - 1)]
                b = everyone[rng.randint(0, len(everyone) - 1)]
                if a != b:
                    src.net.clog_pair(a, b, 2.0 * rng.random())
                if rng.coinflip(0.5):
                    victim = workers[rng.randint(0, len(workers) - 1)]
                    src.net.kill(victim, KillType.RebootProcess)
        ftask = src.loop.spawn(faults(), name="fcFaults")

        a1 = src.loop.spawn(agent.run_agent(), name="agent1")
        tailer = src.loop.spawn(agent.run_log_tailer(), name="tailer")
        await a1
        await ftask
        src.net.heal()
        src.net.reboot_dead([p.address for p in src.cluster_procs()])
        await src.loop.delay(1.0)

        # quiesce the writer BEFORE stopping so the end version covers
        # every landed write, then capture source truth at end version
        state["stop"] = True
        await wtask
        end_version = await agent.stop()
        await tailer

        async def readall(tr):
            tr._read_version = end_version
            return await tr.get_range(b"", b"\xff")
        return _user_rows(await db.transact(readall, max_retries=500))

    truth = src.run(src.loop.spawn(t()), max_time=600_000.0)
    assert len(truth) > 40, "fault cocktail starved the workload"

    dst = SimCluster(seed=44, n_proxies=1, n_resolvers=1, n_tlogs=1,
                     n_storage=2)
    db2 = dst.database()

    async def r():
        await RestoreAgent(db2, container).restore()

        async def readall(tr):
            return await tr.get_range(b"", b"\xff")
        return _user_rows(await db2.transact(readall, max_retries=200))

    got = dst.run(dst.loop.spawn(r()), max_time=600_000.0)
    assert got == truth, (
        f"restore mismatch under faults: {len(got)} vs {len(truth)} rows; "
        f"missing={set(dict(truth)) - set(dict(got))} "
        f"extra={set(dict(got)) - set(dict(truth))}")
