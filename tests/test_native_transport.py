"""Native transport plane: C framer/parser vs Python framer parity.

Three-way parity contract (ISSUE: the C plane must be held byte-identical
to the Python framer): (1) frame assembly — `native_transport.py_frame`
(pure Python) and the C `transport_frame` produce identical bytes; (2)
stream parsing — a reference Python parser (mirroring transport.py's
_read_raw_frame/_verify_and_load decisions) and `TransportConn.feed` split
any byte stream, torn/corrupted/oversized included, into identical frames
with identical reject decisions and identical residue; (3) fast-path
replies — the C storage/GRV serves answer with frames byte-identical to
`wire.dumps` of the reply objects the Python handlers would send.

The fuzz bodies (fuzz_*) are imported by scripts/native_sanitize_fuzz.py
stage 5 and re-run under ASan/UBSan — keep this module outside the jax
import closure (no transport.py/knobs at module scope).
"""

import random
import struct

import pytest

from foundationdb_tpu import native
from foundationdb_tpu.net import native_transport as nt
from foundationdb_tpu.server import interfaces as si
from foundationdb_tpu.utils import wire

HAVE_NATIVE = nt.available()
pytestmark = pytest.mark.skipif(
    not HAVE_NATIVE, reason="C extension lacks the transport plane")

_REQUEST, _REPLY, _REPLY_ERROR, _ONE_WAY = 0, 1, 2, 3
TOO_OLD = "transaction_too_old"


# -- (1) frame assembly parity ------------------------------------------------

def fuzz_frame_parity(seed: int, iters: int = 200):
    """py_frame == C transport_frame, bit for bit, and the header fields
    and CRC-32C of both parse back exactly."""
    rng = random.Random(seed)
    for _ in range(iters):
        token = rng.getrandbits(64)
        reply_id = rng.getrandbits(64)
        kind = rng.choice((0, 1, 2, 3, rng.randrange(256)))
        body = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 600)))
        a = nt.py_frame(token, reply_id, kind, body)
        b = native.mod.transport_frame(token, reply_id, kind, body)
        assert a == b
        length, tok, rid, k, crc = nt._HEADER.unpack(a[:nt.HEADER_LEN])
        assert (length, tok, rid, k) == (len(body), token, reply_id, kind)
        assert a[nt.HEADER_LEN:] == body
        assert crc == nt._py_crc32c(body) == native.mod.crc32c(body, 0)


def test_frame_parity_fuzz():
    for seed in (1, 2):
        fuzz_frame_parity(seed)


def test_oversized_body_rejected_by_both_framers():
    big = b"\x00" * (nt.MAX_FRAME_BYTES + 1)
    with pytest.raises(ValueError):
        nt.py_frame(1, 1, 0, big)
    with pytest.raises(ValueError):
        native.mod.transport_frame(1, 1, 0, big)


def test_crc32c_known_answer():
    # the Castagnoli check vector — a plain CRC-32 (0x04C11DB7) would give
    # 0xCBF43926 here instead, so this pins the polynomial on both sides
    assert nt._py_crc32c(b"123456789") == 0xE3069283
    assert native.mod.crc32c(b"123456789", 0) == 0xE3069283


# -- (2) stream parse + reject parity -----------------------------------------

def _py_parse_stream(data: bytes):
    """Reference stream parser: transport.py's per-frame decisions
    (_read_raw_frame bounds check, then CRC) applied to a whole buffer.
    Returns (frames, err, residue): frames as (token, reply_id, kind,
    body), err the reject decision string or None, residue the unconsumed
    tail (meaningful only when err is None)."""
    frames = []
    pos = 0
    while True:
        if len(data) - pos < nt.HEADER_LEN:
            return frames, None, data[pos:]
        length, token, reply_id, kind, crc = nt._HEADER.unpack_from(data, pos)
        if length > nt.MAX_FRAME_BYTES:
            return frames, "oversized frame", b""
        if len(data) - pos - nt.HEADER_LEN < length:
            return frames, None, data[pos:]
        body = data[pos + nt.HEADER_LEN:pos + nt.HEADER_LEN + length]
        if nt._py_crc32c(body) != crc:
            return frames, "packet checksum mismatch", b""
        frames.append((token, reply_id, kind, body))
        pos += nt.HEADER_LEN + length


def _feed_chunked(conn, data: bytes, rng):
    """Feed `data` to a TransportConn in random-size chunks; returns the
    accumulated (slow_frames, err). Stops at the first err (the connection
    is dead, matching the serve loop dropping it)."""
    slow_all = []
    pos = 0
    while pos < len(data):
        n = rng.randrange(1, max(2, len(data) - pos + 1))
        replies, slow, err = conn.feed(data[pos:pos + n])
        assert replies is None  # empty table: nothing fast-serves
        slow_all.extend(slow)
        if err is not None:
            return slow_all, err
        pos += n
    return slow_all, None


def fuzz_stream_reject_parity(seed: int, streams: int = 40):
    """Random frame streams — good frames, corrupted CRC, oversized
    headers, unknown kinds, torn tails — split identically by the
    reference Python parser and TransportConn.feed under random chunking:
    same frames out, same reject decision, same residue."""
    rng = random.Random(seed)
    for _ in range(streams):
        parts = []
        for _f in range(rng.randrange(0, 6)):
            body = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 120)))
            frame = nt.py_frame(rng.getrandbits(64), rng.getrandbits(64),
                                rng.randrange(256), body)
            shape = rng.randrange(6)
            if shape == 0:  # corrupted CRC / body byte
                i = rng.randrange(nt.HEADER_LEN - 4, len(frame))
                frame = frame[:i] + bytes([frame[i] ^ 0x20]) + frame[i + 1:]
            elif shape == 1:  # oversized length claim
                frame = struct.pack(">I", nt.MAX_FRAME_BYTES
                                    + rng.randrange(1, 1 << 20)) + frame[4:]
            elif shape == 2:  # max-size length claim, body absent: torn
                frame = struct.pack(">I", nt.MAX_FRAME_BYTES) + frame[4:]
            parts.append(frame)
        data = b"".join(parts)
        if rng.randrange(2):  # torn tail
            data = data[:max(0, len(data) - rng.randrange(1, 30))]

        want_frames, want_err, want_residue = _py_parse_stream(data)
        conn = nt.new_conn(nt.new_table())
        got_frames, got_err = _feed_chunked(conn, data, rng)
        assert got_frames == want_frames
        assert got_err == want_err
        if want_err is None:
            assert conn.residue() == want_residue


def test_stream_reject_parity_fuzz():
    for seed in (3, 4):
        fuzz_stream_reject_parity(seed)


def test_dead_conn_refuses_more_input():
    conn = nt.new_conn(nt.new_table())
    bad = nt.py_frame(1, 1, 0, b"x")
    bad = bad[:-1] + bytes([bad[-1] ^ 1])  # corrupt the body
    _replies, _slow, err = conn.feed(bad)
    assert err == "packet checksum mismatch"
    with pytest.raises(ValueError):
        conn.feed(b"more")


# -- (3) fast-path reply byte parity ------------------------------------------

def _fge(key: bytes) -> si.KeySelector:
    return si.KeySelector(key=key, or_equal=False, offset=1)


def _request_frame(table_token, reply_id, payload) -> bytes:
    return nt.py_frame(table_token, reply_id, _REQUEST, wire.dumps(payload))


def _expect_reply(reply_id, payload) -> bytes:
    return nt.py_frame(0, reply_id, _REPLY, wire.dumps(payload))


def _expect_error(reply_id, name) -> bytes:
    return nt.py_frame(0, reply_id, _REPLY_ERROR, wire.dumps(name))


def _build_store(rng, keys, versions):
    """A VStore plus the pure-Python model of it: {key: [(v, val)...]}."""
    vs = native.mod.VStore()
    model = {}
    for k in keys:
        for v in sorted(rng.sample(versions, rng.randrange(1, 4))):
            val = (None if rng.random() < 0.2 else
                   bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 20))))
            vs.put(k, v, val)
            model.setdefault(k, []).append((v, val))
    return vs, model


def _model_get(model, key, version):
    best = None
    for v, val in model.get(key, ()):
        if v <= version:
            best = val
    return best


def fuzz_fast_path_parity(seed: int, iters: int = 60):
    """The C storage serves answer byte-identically to wire.dumps of the
    reply objects the Python storage handlers produce — checked against an
    independent pure-Python MVCC model, not against the C store's own
    encoders."""
    rng = random.Random(seed)
    tok_gv, tok_gvs, tok_gkv = si.Token.STORAGE_GET_VALUE, \
        si.Token.STORAGE_GET_VALUES, si.Token.STORAGE_GET_KEY_VALUES
    oldest, latest = 5, 15
    keys = [b"k%02d" % i for i in range(12)]
    vs, model = _build_store(rng, keys, list(range(1, latest + 1)))
    table = nt.new_table()
    table.enable_storage(vs, *nt.storage_wire_ids(),
                         oldest, latest, 10**9)
    conn = nt.new_conn(table)
    rid = 0
    for _ in range(iters):
        rid += 1
        shape = rng.randrange(4)
        if shape == 0:  # GetValue within the window
            key = rng.choice(keys + [b"absent"])
            ver = rng.randrange(oldest, latest + 1)
            req = _request_frame(tok_gv, rid,
                                 si.GetValueRequest(key=key, version=ver))
            want = _expect_reply(rid, si.GetValueReply(
                value=_model_get(model, key, ver), version=ver))
        elif shape == 1:  # GetValue outside the MVCC window
            ver = rng.choice((oldest - 1, 0))
            req = _request_frame(tok_gv, rid, si.GetValueRequest(
                key=rng.choice(keys), version=ver))
            want = _expect_error(rid, TOO_OLD)
        elif shape == 2:  # GetValues batch, mixed per-item outcomes
            reads = [(rng.choice(keys),
                      rng.randrange(oldest - 2, latest + 1))
                     for _ in range(rng.randrange(1, 5))]
            req = _request_frame(tok_gvs, rid,
                                 si.GetValuesRequest(reads=reads))
            if max(v for _k, v in reads) < oldest:
                want = _expect_error(rid, TOO_OLD)
            else:
                results = [(1, TOO_OLD) if v < oldest
                           else (0, _model_get(model, k, v))
                           for k, v in reads]
                want = _expect_reply(rid, si.GetValuesReply(results=results))
        else:  # GetKeyValues over FGE selectors
            b, e = sorted((rng.choice(keys + [b""]),
                           rng.choice(keys + [b"\xff"])))
            ver = rng.randrange(oldest, latest + 1)
            reverse = rng.random() < 0.5
            rows = [(k, _model_get(model, k, ver))
                    for k in keys if b <= k < e
                    and _model_get(model, k, ver) is not None]
            if reverse:
                rows.reverse()
            limit = rng.choice((0, 0, rng.randrange(1, 6)))
            more = bool(limit) and len(rows) > limit
            if limit:
                rows = rows[:limit]
            req = _request_frame(tok_gkv, rid, si.GetKeyValuesRequest(
                begin=_fge(b), end=_fge(e), version=ver, limit=limit,
                limit_bytes=0, reverse=reverse))
            want = _expect_reply(rid, si.GetKeyValuesReply(
                data=rows, more=more, version=ver))
        replies, slow, err = conn.feed(req)
        assert err is None and slow == []
        assert replies == want, (shape, rid)


def test_fast_path_parity_fuzz():
    for seed in (5, 6):
        fuzz_fast_path_parity(seed)


def test_future_version_falls_to_python():
    """A read above the pushed latest bound must NOT be answered by the C
    plane — Python owns version waits — and shard-mode disable stands the
    plane down entirely."""
    vs = native.mod.VStore()
    vs.put(b"k", 5, b"v")
    table = nt.new_table()
    table.enable_storage(vs, *nt.storage_wire_ids(), 1, 10, 10**9)
    conn = nt.new_conn(table)
    req = _request_frame(si.Token.STORAGE_GET_VALUE, 1,
                         si.GetValueRequest(key=b"k", version=11))
    replies, slow, err = conn.feed(req)
    assert replies is None and err is None and len(slow) == 1
    assert slow[0][:3] == (si.Token.STORAGE_GET_VALUE, 1, _REQUEST)

    # bounds move with durability/GC: push, then the same version serves
    table.set_read_bounds(1, 11)
    replies, slow, err = conn.feed(req)
    assert err is None and slow == []
    assert replies == _expect_reply(1, si.GetValueReply(value=b"v",
                                                        version=11))

    table.disable_storage()
    replies, slow, err = conn.feed(req)
    assert replies is None and len(slow) == 1


def test_grv_fast_path_allowance_and_priority():
    table = nt.new_table()
    table.enable_grv(*nt.grv_wire_ids())
    conn = nt.new_conn(table)

    def grv(rid, priority=0, debug_id=None):
        return conn.feed(_request_frame(
            si.Token.PROXY_GET_READ_VERSION, rid,
            si.GetReadVersionRequest(priority=priority, debug_id=debug_id)))

    # no version pushed yet: falls to Python
    replies, slow, err = grv(1)
    assert replies is None and len(slow) == 1 and err is None

    table.set_grv(42, 3)
    replies, slow, err = grv(2)
    assert slow == [] and err is None
    assert replies == _expect_reply(2, si.GetReadVersionReply(version=42))

    # non-default priority is ratekeeper policy: Python's call
    replies, slow, err = grv(3, priority=1)
    assert replies is None and len(slow) == 1

    # the client stamps a span id on every real-path GRV; the handler
    # never reads it, so the plane serves through it
    replies, slow, err = grv(4, debug_id="grv-1f3a")
    assert slow == [] and err is None
    assert replies == _expect_reply(4, si.GetReadVersionReply(version=42))

    replies, _slow, _err = grv(5)
    assert replies == _expect_reply(5, si.GetReadVersionReply(version=42))
    # allowance exhausted (3 granted): the plane stops handing out
    replies, slow, _err = grv(6)
    assert replies is None and len(slow) == 1
    assert table.counters()["NativeGRVHits"] == 3


def test_counters_track_frames_and_hits():
    vs = native.mod.VStore()
    vs.put(b"a", 3, b"1")
    table = nt.new_table()
    table.enable_storage(vs, *nt.storage_wire_ids(), 1, 5, 10**9)
    conn = nt.new_conn(table)
    served = _request_frame(si.Token.STORAGE_GET_VALUE, 1,
                            si.GetValueRequest(key=b"a", version=3))
    fell = nt.py_frame(999, 2, _REQUEST, wire.dumps("nope"))
    replies, slow, err = conn.feed(served + fell)
    assert err is None and len(slow) == 1 and replies is not None
    c = table.counters()
    assert c["FramesIn"] == 2 and c["FramesOut"] == 1
    assert c["NativeFastPathHits"] == 1 and c["NativeGetValueHits"] == 1
    assert c["PySlowPathFalls"] == 1 and c["ChecksumRejects"] == 0
    assert c["BytesIn"] == len(served) + len(fell)
    assert c["BytesOut"] == len(replies)


# -- end-to-end over the real wire --------------------------------------------

def _free_addr():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return addr


def test_native_plane_serves_over_real_wire(monkeypatch):
    """Proof the C plane answers on a live connection: the server registers
    NO Python handler for the storage/GRV tokens, so any reply the client
    gets can only have come from the native fast path — and it must parse
    and CRC-verify on the client's pure-Python reply reader."""
    monkeypatch.setenv("NET_NATIVE_TRANSPORT", "1")
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop

    loop = RealEventLoop()
    srv = NetTransport(loop, _free_addr())
    cli = NetTransport(loop, _free_addr())
    srv.start()
    cli.start()
    try:
        assert srv.native_table is not None
        vs = native.mod.VStore()
        vs.put(b"hello", 7, b"native")
        srv.native_table.enable_storage(vs, *nt.storage_wire_ids(),
                                        1, 10, 10**9)
        srv.native_table.enable_grv(*nt.grv_wire_ids())
        srv.native_table.set_grv(77, 100)

        async def reads():
            gv = await cli.request(
                cli.process,
                Endpoint(srv.address, si.Token.STORAGE_GET_VALUE),
                si.GetValueRequest(key=b"hello", version=7))
            grv = await cli.request(
                cli.process,
                Endpoint(srv.address, si.Token.PROXY_GET_READ_VERSION),
                si.GetReadVersionRequest(debug_id="span-g1"))
            return gv, grv

        gv, grv = loop.run_future(loop.spawn(reads()), max_time=15.0)
        assert (gv.value, gv.version) == (b"native", 7)
        assert grv.version == 77
        c = srv.transport_counters()
        assert c["NativeFastPathHits"] == 2
        assert c["NativeGetValueHits"] == 1 and c["NativeGRVHits"] == 1
        assert c["FramesIn"] >= 2 and c["ChecksumRejects"] == 0
    finally:
        srv.close()
        cli.close()


def test_native_fault_degrades_connection_to_python(monkeypatch):
    """The per-connection fallback contract: a native-plane fault mid-
    stream downgrades just that connection to the Python serve loop, which
    replays the plane's buffered residue — the in-flight request still
    gets its answer."""
    monkeypatch.setenv("NET_NATIVE_TRANSPORT", "1")
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop

    class FaultyConn:
        def __init__(self):
            self.buf = b""

        def feed(self, chunk):
            self.buf += bytes(chunk)
            raise RuntimeError("injected native fault")

        def residue(self):
            return self.buf

    monkeypatch.setattr(nt, "new_conn", lambda table: FaultyConn())

    loop = RealEventLoop()
    srv = NetTransport(loop, _free_addr())
    cli = NetTransport(loop, _free_addr())
    srv.start()
    cli.start()
    try:
        assert srv.native_table is not None
        srv.process.register(42, lambda payload, reply: reply.send(
            payload + 1))

        async def call():
            return await cli.request(cli.process,
                                     Endpoint(srv.address, 42), 10)
        assert loop.run_future(loop.spawn(call()), max_time=15.0) == 11
        assert srv.transport_counters()["PySlowPathFalls"] >= 1
    finally:
        srv.close()
        cli.close()


@pytest.mark.parametrize("native_on", ["1", "0"])
def test_checksum_reject_drops_the_tcp_connection(monkeypatch, native_on):
    """A protocol reject must reach the TCP layer on both planes: the
    serve loop's drop decision has to close the socket so the peer sees
    EOF instead of hanging on recv forever (regression for the reject
    path leaving the writer open)."""
    import asyncio

    monkeypatch.setenv("NET_NATIVE_TRANSPORT", native_on)
    from foundationdb_tpu.net import transport as T

    loop = T.RealEventLoop()
    srv = T.NetTransport(loop, _free_addr())
    srv.start()
    try:
        assert (srv.native_table is not None) == (native_on == "1")
        host, port = srv.address.rsplit(":", 1)

        async def probe():
            r, w = await asyncio.open_connection(host, int(port))
            w.write(T._CONNECT)
            bad = bytearray(srv._frame(7, 1, T._REQUEST, wire.dumps(None)))
            bad[21] ^= 0xFF  # corrupt the stored CRC-32C
            w.write(bytes(bad))
            await w.drain()
            data = await asyncio.wait_for(r.read(64), timeout=10.0)
            w.close()
            return data

        assert loop.aio.run_until_complete(probe()) == b""
        assert srv.transport_counters()["ChecksumRejects"] == 1
    finally:
        srv.close()


def test_read_replies_verifies_checksum_exactly_once(monkeypatch):
    """Satellite regression: the client reply reader must verify a frame's
    CRC at most once, and not at all for a retransmit-dedup hit (a reply
    whose request already completed or expired) — those bytes are dropped
    unread, so checksumming them is pure event-loop burn."""
    import asyncio

    from foundationdb_tpu.core.future import Promise
    from foundationdb_tpu.net import transport as T

    calls = []
    real = nt.crc32c
    monkeypatch.setattr(nt, "crc32c",
                        lambda body, crc=0: calls.append(len(body))
                        or real(body, crc))

    loop = T.RealEventLoop()
    t = T.NetTransport(loop, "127.0.0.1:1")  # never started: pure framing
    pending = Promise()
    t._pending[5] = (pending, "10.0.0.9:4000", None)
    live = t._frame(0, 5, T._REPLY, wire.dumps("served"))
    dedup = t._frame(0, 99, T._REPLY, wire.dumps("dropped"))

    async def go():
        r = asyncio.StreamReader()
        r.feed_data(dedup + live)
        r.feed_eof()
        await t._read_replies(r, "10.0.0.9:4000")

    loop.aio.run_until_complete(go())
    assert pending.future.is_ready()
    assert pending.future.get() == "served"
    # exactly one verification, for the one frame somebody read
    assert calls == [len(live) - nt.HEADER_LEN]


def test_read_replies_crc_reject_fails_popped_entry():
    """A reply frame that fails its checksum AFTER its pending entry was
    popped must fail that entry (broken_promise), not strand it until the
    RPC timeout."""
    import asyncio

    from foundationdb_tpu.core.future import Promise
    from foundationdb_tpu.net import transport as T

    loop = T.RealEventLoop()
    t = T.NetTransport(loop, "127.0.0.1:1")
    pending = Promise()
    t._pending[5] = (pending, "10.0.0.9:4000", None)
    frame = t._frame(0, 5, T._REPLY, wire.dumps("x"))
    frame = frame[:-1] + bytes([frame[-1] ^ 1])  # corrupt the body

    async def go():
        r = asyncio.StreamReader()
        r.feed_data(frame)
        r.feed_eof()
        await t._read_replies(r, "10.0.0.9:4000")

    loop.aio.run_until_complete(go())
    fut = pending.future
    assert fut.is_ready() and fut.is_error()
    assert fut._result.name == "broken_promise"
    assert t._c_checksum_rejects == 1
    assert not t._pending
