"""End-to-end tests of the full commit pipeline under the simulator.

Mirrors the reference's workload strategy (SURVEY.md §4): correctness
invariants driven through the public Transaction API against a whole simulated
cluster — not unit mocks. Reference workloads modeled here: Cycle
(fdbserver/workloads/Cycle.actor.cpp serializability ring), AtomicOps,
WriteDuringRead (RYW semantics), Watches.
"""

import pytest

from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.server.interfaces import KeySelector
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.types import MutationType


def make_cluster(**kw):
    kw.setdefault("seed", 1)
    return SimCluster(**kw)


@pytest.fixture(autouse=True)
def _oracle_backend():
    # e2e tests run the CPU oracle conflict backend for speed; the device
    # backend's decision parity is covered by tests/test_conflict.py
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def test_set_and_get_roundtrip():
    c = make_cluster()
    db = c.database()

    async def writer():
        tr = db.create_transaction()
        tr.set(b"hello", b"world")
        tr.set(b"foo", b"bar")
        await tr.commit()
        assert tr.committed_version is not None and tr.committed_version > 0

    async def reader():
        tr = db.create_transaction()
        assert await tr.get(b"hello") == b"world"
        assert await tr.get(b"foo") == b"bar"
        assert await tr.get(b"missing") is None

    c.run(c.loop.spawn(writer()))
    c.run(c.loop.spawn(reader()))


def test_read_your_writes_and_clears():
    c = make_cluster()
    db = c.database()

    async def t():
        tr = db.create_transaction()
        tr.set(b"a", b"1")
        assert await tr.get(b"a") == b"1"  # uncommitted write visible
        tr.clear(b"a")
        assert await tr.get(b"a") is None
        tr.set(b"b", b"2")
        tr.clear_range(b"a", b"c")
        assert await tr.get(b"b") is None
        tr.set(b"b", b"3")  # set after clear wins
        assert await tr.get(b"b") == b"3"
        await tr.commit()

        tr2 = db.create_transaction()
        assert await tr2.get(b"a") is None
        assert await tr2.get(b"b") == b"3"

    c.run(c.loop.spawn(t()))


def test_conflict_between_transactions():
    c = make_cluster()
    db = c.database()
    outcome = {}

    async def t():
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        # both read k, both write k: second committer must abort
        await t1.get(b"k")
        await t2.get(b"k")
        t1.set(b"k", b"t1")
        t2.set(b"k", b"t2")
        await t1.commit()
        try:
            await t2.commit()
            outcome["t2"] = "committed"
        except FDBError as e:
            outcome["t2"] = e.name

    c.run(c.loop.spawn(t()))
    assert outcome["t2"] == "not_committed"


def test_snapshot_read_does_not_conflict():
    c = make_cluster()
    db = c.database()

    async def t():
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        await t1.get(b"k", snapshot=True)  # snapshot: no read conflict
        await t2.get(b"k")
        t1.set(b"k", b"t1")
        t2.set(b"other", b"x")
        await t2.commit()
        await t1.commit()  # would abort if the read were conflict-checked

    c.run(c.loop.spawn(t()))


def test_transact_retry_loop():
    c = make_cluster()
    db = c.database()
    counter_key = b"counter"

    async def incr(tr):
        v = await tr.get(counter_key)
        n = int(v or b"0")
        tr.set(counter_key, str(n + 1).encode())

    async def t():
        # 10 concurrent increments; the retry loop must serialize them
        from foundationdb_tpu.core.future import all_of
        tasks = [c.loop.spawn(db.transact(incr), name=f"incr{i}")
                 for i in range(10)]
        await all_of(tasks)
        tr = db.create_transaction()
        assert await tr.get(counter_key) == b"10"

    c.run(c.loop.spawn(t()), max_time=10_000.0)


def test_atomic_ops():
    c = make_cluster()
    db = c.database()

    async def t():
        tr = db.create_transaction()
        tr.atomic_op(MutationType.ADD_VALUE, b"n", (5).to_bytes(8, "little"))
        await tr.commit()
        tr = db.create_transaction()
        tr.atomic_op(MutationType.ADD_VALUE, b"n", (7).to_bytes(8, "little"))
        # RYW of an unresolved atomic op fetches the base and applies
        assert int.from_bytes((await tr.get(b"n")), "little") == 12
        await tr.commit()
        tr = db.create_transaction()
        assert int.from_bytes((await tr.get(b"n")), "little") == 12
        tr.atomic_op(MutationType.BYTE_MAX, b"s", b"mmm")
        await tr.commit()
        tr = db.create_transaction()
        tr.atomic_op(MutationType.BYTE_MAX, b"s", b"zzz")
        await tr.commit()
        tr = db.create_transaction()
        assert await tr.get(b"s") == b"zzz"

    c.run(c.loop.spawn(t()))


def test_range_reads_with_selectors_and_limits():
    c = make_cluster()
    db = c.database()

    async def t():
        tr = db.create_transaction()
        for i in range(20):
            tr.set(b"k%02d" % i, b"v%d" % i)
        await tr.commit()

        tr = db.create_transaction()
        rows = await tr.get_range(b"k05", b"k10")
        assert [k for k, _ in rows] == [b"k05", b"k06", b"k07", b"k08", b"k09"]
        rows = await tr.get_range(b"k05", b"k10", limit=2)
        assert [k for k, _ in rows] == [b"k05", b"k06"]
        rows = await tr.get_range(b"k05", b"k10", reverse=True, limit=2)
        assert [k for k, _ in rows] == [b"k09", b"k08"]
        # RYW merge inside a range
        tr.set(b"k07x", b"new")
        tr.clear(b"k06")
        rows = await tr.get_range(b"k05", b"k09")
        assert [k for k, _ in rows] == [b"k05", b"k07", b"k07x", b"k08"]
        # selectors (resolved against the RYW view: k06 is cleared above)
        k = await tr.get_key(KeySelector.first_greater_than(b"k05"))
        assert k == b"k07"
        k = await tr.get_key(KeySelector.last_less_than(b"k05"))
        assert k == b"k04"
        k = await tr.get_key(KeySelector.first_greater_or_equal(b"k06"))
        assert k == b"k07"

    c.run(c.loop.spawn(t()))


def test_versionstamped_value():
    c = make_cluster()
    db = c.database()

    async def t():
        tr = db.create_transaction()
        # value = 10 placeholder bytes + 4-byte LE offset 0
        tr.atomic_op(MutationType.SET_VERSIONSTAMPED_VALUE, b"vs",
                     b"\x00" * 10 + (0).to_bytes(4, "little"))
        await tr.commit()
        cv = tr.committed_version
        tr = db.create_transaction()
        v = await tr.get(b"vs")
        assert len(v) == 10
        assert int.from_bytes(v[:8], "big") == cv

    c.run(c.loop.spawn(t()))


def test_watch_fires_on_change():
    c = make_cluster()
    db = c.database()
    fired = {}

    async def t():
        tr = db.create_transaction()
        tr.set(b"w", b"0")
        await tr.commit()

        tr = db.create_transaction()
        w = await tr.watch(b"w")
        assert not w.is_ready()

        tr2 = db.create_transaction()
        tr2.set(b"w", b"1")
        await tr2.commit()
        await w
        fired["ok"] = True

    c.run(c.loop.spawn(t()))
    assert fired.get("ok")


def test_cycle_workload_serializability():
    """Cycle workload (Cycle.actor.cpp:27-80): N keys form a ring by value;
    transactional 3-key rotations must preserve the ring invariant."""
    c = make_cluster()
    db = c.database()
    N = 6

    def key(i):
        return b"cycle/%02d" % i

    async def setup(tr):
        for i in range(N):
            tr.set(key(i), b"%02d" % ((i + 1) % N))

    async def rotate(tr):
        # pick a random start, rotate the chain a->b->c to a->c->b's target
        r = c.rng.randint(0, N - 1)
        a = key(r)
        b_idx = int(await tr.get(a))
        b = key(b_idx)
        c_idx = int(await tr.get(b))
        cc = key(c_idx)
        d_idx = int(await tr.get(cc))
        tr.set(a, b"%02d" % c_idx)
        tr.set(b, b"%02d" % d_idx)
        tr.set(cc, b"%02d" % b_idx)

    async def check():
        tr = db.create_transaction()
        seen = set()
        i = 0
        for _ in range(N):
            seen.add(i)
            i = int(await tr.get(key(i)))
        assert i == 0 and len(seen) == N, f"ring broken: {seen}"

    async def t():
        await db.transact(setup)
        from foundationdb_tpu.core.future import all_of
        tasks = [c.loop.spawn(db.transact(rotate), name=f"rot{i}")
                 for i in range(20)]
        await all_of(tasks)
        await check()

    c.run(c.loop.spawn(t()), max_time=10_000.0)


def test_too_old_transaction():
    c = make_cluster()
    db = c.database()

    async def t():
        tr_old = db.create_transaction()
        await tr_old.get(b"x")  # pins an early read version

        # push many committed versions past the MVCC window
        KNOBS.set("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 1000)
        tr = db.create_transaction()
        tr.set(b"x", b"1")
        await tr.commit()
        # advance virtual time so the next commit version jumps the window
        await c.loop.delay(1.0)  # 1s = 1e6 versions >> 1000
        tr = db.create_transaction()
        tr.set(b"x", b"2")
        await tr.commit()

        tr_old.set(b"x", b"old")
        try:
            await tr_old.commit()
            raise AssertionError("expected transaction_too_old")
        except FDBError as e:
            assert e.name == "transaction_too_old"

    c.run(c.loop.spawn(t()))


def test_multi_resolver_commit():
    """Conflict ranges split across resolvers; commit iff all agree."""
    c = make_cluster(n_resolvers=4)
    db = c.database()

    async def t():
        tr = db.create_transaction()
        # writes spanning all resolver partitions
        for prefix in (b"\x01", b"\x41", b"\x81", b"\xc1"):
            tr.set(prefix + b"key", b"v")
        await tr.commit()

        t1 = db.create_transaction()
        t2 = db.create_transaction()
        await t1.get(b"\x01key")
        await t2.get(b"\xc1key")
        t1.set(b"\xc1key", b"t1")  # t1 writes what t2 read
        t2.set(b"\x01key", b"t2")  # t2 writes what t1 read
        await t1.commit()
        try:
            await t2.commit()
            raise AssertionError("expected not_committed")
        except FDBError as e:
            assert e.name == "not_committed"

    c.run(c.loop.spawn(t()))


def test_multi_tlog_quorum_and_multi_storage():
    c = make_cluster(n_tlogs=2, n_storage=2)
    db = c.database()

    async def t():
        tr = db.create_transaction()
        tr.set(b"\x01a", b"shard0")
        tr.set(b"\x90z", b"shard1")
        await tr.commit()
        tr = db.create_transaction()
        assert await tr.get(b"\x01a") == b"shard0"
        assert await tr.get(b"\x90z") == b"shard1"

    c.run(c.loop.spawn(t()))


def test_determinism_same_seed_same_trace():
    def run_once(seed):
        c = make_cluster(seed=seed)
        db = c.database()
        log = []

        async def t():
            for i in range(5):
                tr = db.create_transaction()
                tr.set(b"k%d" % i, b"v")
                await tr.commit()
                log.append((i, tr.committed_version, c.loop.now()))

        c.run(c.loop.spawn(t()))
        return log

    assert run_once(7) == run_once(7)
    assert run_once(7) != run_once(8)  # different seed -> different timings


def test_limited_range_read_survives_overlay_clears():
    """Regression: overlay clears must not starve a limited range read —
    the client continues fetching past limit-cut storage replies."""
    c = make_cluster()
    db = c.database()

    async def t():
        tr = db.create_transaction()
        for i in range(20):
            tr.set(b"k%02d" % i, b"v")
        await tr.commit()

        tr = db.create_transaction()
        tr.clear_range(b"k00", b"k15")  # clears everything a small fetch sees
        rows = await tr.get_range(b"k00", b"k99", limit=3)
        assert [k for k, _ in rows] == [b"k15", b"k16", b"k17"]
        rows = await tr.get_range(b"k00", b"k99", limit=3, reverse=True)
        assert [k for k, _ in rows] == [b"k19", b"k18", b"k17"]

    c.run(c.loop.spawn(t()))


def test_multi_proxy_read_after_commit():
    """Regression: GRV must confirm committed versions across ALL proxies
    (getLiveCommittedVersion), or a read can miss the client's own commit."""
    c = make_cluster(n_proxies=3)
    db = c.database()

    async def t():
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"rac", b"%d" % i)
            await tr.commit()
            tr2 = db.create_transaction()  # may hit a different proxy
            assert await tr2.get(b"rac") == b"%d" % i

    c.run(c.loop.spawn(t()))


def test_backward_end_selector_with_overlay():
    """Regression: backward/non-canonical end selectors resolve against the
    merged RYW view, not a conservative byte ceiling."""
    c = make_cluster()
    db = c.database()

    async def t():
        tr = db.create_transaction()
        tr.set(b"a", b"1")
        tr.set(b"b", b"2")
        await tr.commit()
        tr = db.create_transaction()
        tr.set(b"y", b"3")  # overlay key beyond the resolved end
        rows = await tr.get_range(b"a", KeySelector.last_less_than(b"z"))
        # end resolves to y (merged view); range [a, y) -> a, b
        assert [k for k, _ in rows] == [b"a", b"b"]

    c.run(c.loop.spawn(t()))


def test_cross_shard_range_reads():
    """Range reads spanning 4 storage shards return exactly the right rows
    in both directions, with and without limits (NativeAPI
    getKeyRangeLocations :1083 + wrong_shard_server contract). Round 1 routed
    a range to its begin-key owner only, silently truncating the result."""
    c = make_cluster(n_storage=4, n_tlogs=2)
    db = c.database()
    # keys spread across all 4 shards (boundaries at 0x40, 0x80, 0xc0)
    keys = [bytes([16 * i]) + b"/k%02d" % i for i in range(16)]

    async def t():
        async def setup(tr):
            for i, k in enumerate(keys):
                tr.set(k, b"v%02d" % i)
        await db.transact(setup)

        tr = db.create_transaction()
        rows = await tr.get_range(b"", b"\xff")
        assert [k for k, _v in rows] == keys
        assert [v for _k, v in rows] == [b"v%02d" % i for i in range(16)]

        rows = await tr.get_range(b"", b"\xff", reverse=True)
        assert [k for k, _v in rows] == keys[::-1]

        # limited reads stopping mid-shard and mid-keyspace
        rows = await tr.get_range(b"", b"\xff", limit=5)
        assert [k for k, _v in rows] == keys[:5]
        rows = await tr.get_range(b"", b"\xff", limit=11, reverse=True)
        assert [k for k, _v in rows] == keys[::-1][:11]

        # window straddling two shard boundaries
        rows = await tr.get_range(keys[2], keys[13])
        assert [k for k, _v in rows] == keys[2:13]

        # selector resolution across shards
        from foundationdb_tpu.server.interfaces import KeySelector
        k = await tr.get_key(KeySelector.first_greater_than(keys[6]))
        assert k == keys[7]

    c.run(c.loop.spawn(t()), max_time=5_000.0)


def test_wrong_shard_server_rejected():
    """A read routed to the wrong storage server must error, not silently
    return rows from the wrong shard (the server-side half of the
    location-cache contract)."""
    c = make_cluster(n_storage=2)
    db = c.database()

    async def t():
        async def setup(tr):
            tr.set(b"\x10a", b"1")
            tr.set(b"\xf0b", b"2")
        await db.transact(setup)
        # corrupt the location cache: swap the two shard teams
        db.locations.teams = db.locations.teams[::-1]
        tr = db.create_transaction()
        try:
            await tr.get(b"\x10a")
            raise AssertionError("stale-cache read did not error")
        except FDBError as e:
            assert e.name == "wrong_shard_server"

    c.run(c.loop.spawn(t()), max_time=5_000.0)


def test_get_many_matches_individual_gets():
    """tr.get_many returns the same values, in order, as per-key gets —
    on both its paths: the batched fast path (read version known, no
    overlay) and the composed get_future path (no read version yet)."""
    c = make_cluster()
    db = c.database()
    keys = [b"gm%02d" % i for i in range(8)]

    async def t():
        async def setup(tr):
            for i, k in enumerate(keys):
                tr.set(k, b"v%02d" % i)
        await db.transact(setup)

        probe = keys + [b"gm-missing"]
        expect = [b"v%02d" % i for i in range(8)] + [None]

        tr = db.create_transaction()
        assert await tr.get_many(probe) == expect  # no GRV yet: composed

        tr2 = db.create_transaction()
        await tr2.get_read_version()
        assert await tr2.get_many(probe) == expect  # batched fast path
        assert await tr2.get_many([]) == []

    c.run(c.loop.spawn(t()), max_time=5_000.0)


def test_get_many_sees_uncommitted_writes():
    c = make_cluster()
    db = c.database()

    async def t():
        async def setup(tr):
            tr.set(b"a", b"old")
            tr.set(b"b", b"keep")
        await db.transact(setup)

        tr = db.create_transaction()
        await tr.get_read_version()
        tr.set(b"a", b"new")
        tr.clear(b"b")
        assert await tr.get_many([b"a", b"b", b"c"]) == [b"new", None, None]

    c.run(c.loop.spawn(t()), max_time=5_000.0)


def test_get_many_adds_read_conflicts():
    """A non-snapshot multiget must conflict with a concurrent write to any
    of its keys; a snapshot multiget must not."""
    c = make_cluster()
    db = c.database()
    outcome = {}

    async def t():
        t1 = db.create_transaction()
        await t1.get_read_version()
        await t1.get_many([b"k1", b"k2"])
        t2 = db.create_transaction()
        t2.set(b"k2", b"other")
        await t2.commit()
        t1.set(b"unrelated", b"x")
        try:
            await t1.commit()
            outcome["t1"] = "committed"
        except FDBError as e:
            outcome["t1"] = e.name

        t3 = db.create_transaction()
        await t3.get_read_version()
        await t3.get_many([b"k1", b"k2"], snapshot=True)
        t4 = db.create_transaction()
        t4.set(b"k1", b"again")
        await t4.commit()
        t3.set(b"unrelated2", b"y")
        await t3.commit()  # would abort if snapshot reads conflicted

    c.run(c.loop.spawn(t()), max_time=5_000.0)
    assert outcome["t1"] == "not_committed"


def test_get_many_across_shards():
    """A multiget whose keys live on different storage teams decomposes into
    per-team reads and reassembles in order (the _send_read_batches split)."""
    c = make_cluster(n_storage=4, n_tlogs=2)
    db = c.database()
    keys = [bytes([16 * i]) + b"/gm%02d" % i for i in range(16)]

    async def t():
        async def setup(tr):
            for i, k in enumerate(keys):
                tr.set(k, b"v%02d" % i)
        await db.transact(setup)

        tr = db.create_transaction()
        await tr.get_read_version()
        got = await tr.get_many(keys + [b"\x55missing"])
        assert got == [b"v%02d" % i for i in range(16)] + [None]

    c.run(c.loop.spawn(t()), max_time=5_000.0)
