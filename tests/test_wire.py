"""Wire codec: round-trips, schema evolution, and decoder fuzz.

The decode path is the framework's untrusted-input surface (net/transport.py
feeds it raw TCP bytes; flow/serialize.h:188-241 is the reference seam), so
beyond round-trip parity the tests require that arbitrary corrupt bytes can
only raise WireError — never build unregistered types or crash.
"""

import dataclasses
import random

import pytest

from foundationdb_tpu.ops.batch import TxnConflictInfo
from foundationdb_tpu.server import interfaces as I
from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.types import Mutation, MutationType


def rt(obj):
    out = wire.loads(wire.dumps(obj))
    assert out == obj
    return out


def test_primitives_roundtrip():
    rt(None)
    rt(True)
    rt(False)
    rt(0)
    rt(-1)
    rt(1 << 62)
    rt(-(1 << 62))
    rt(123456789123456789123456789)  # arbitrary precision survives
    rt(3.25)
    rt(b"")
    rt(b"\x00\xff" * 100)
    rt("")
    rt("unicode ☃ snowman")
    rt([1, [2, [3, None]], b"x"])
    rt((1, 2, (3,)))
    rt({b"k": [1, 2], "s": {"nested": True}})
    rt({1, 2, 3})


def test_tuple_vs_list_distinct():
    assert isinstance(wire.loads(wire.dumps((1, 2))), tuple)
    assert isinstance(wire.loads(wire.dumps([1, 2])), list)


def test_numpy_scalars_coerce():
    np = pytest.importorskip("numpy")
    assert wire.loads(wire.dumps(np.int64(7))) == 7
    assert wire.loads(wire.dumps(np.int32(-7))) == -7


def test_structs_roundtrip():
    rt(Mutation(MutationType.SET_VALUE, b"k", b"v"))
    rt(I.CommitTransactionRequest(
        read_snapshot=100,
        read_conflict_ranges=[(b"a", b"b")],
        write_conflict_ranges=[(b"a", b"b")],
        mutations=[Mutation(MutationType.CLEAR_RANGE, b"a", b"b")]))
    rt(I.TLogCommitRequest(
        prev_version=1, version=2,
        messages={0: [Mutation(MutationType.SET_VALUE, b"k", b"v")]},
        known_committed_version=1, uid="g1"))
    rt(I.KeySelector.first_greater_than(b"key"))
    rt(I.LogEpoch(begin=0, end=None, addrs=["a:1"], epoch=3, uids=["u"]))
    rt(I.DBInfo(version=1, epoch=2, master="m:1", proxies=["p:1"],
                resolvers=[], log_epochs=[I.LogEpoch(0, None, ["t:1"])],
                storages=[("s:1", 0)], shard_boundaries=[b""],
                shard_tags=[[0]]))
    rt(TxnConflictInfo(read_snapshot=5, read_ranges=[(b"a", b"b")],
                       write_ranges=[]))


def test_enum_identity():
    out = wire.loads(wire.dumps(MutationType.ADD_VALUE))
    assert out is MutationType.ADD_VALUE
    assert isinstance(out, MutationType)


def test_schema_evolution_missing_fields_default():
    """An older peer omits trailing fields; defaults fill in (the protocol-
    version downgrade rule of BinaryReader)."""

    @dataclasses.dataclass
    class V1:
        a: int

    wire.register(1000, V1)
    try:
        old = wire.dumps(V1(7))

        # simulate the same id now having more (defaulted) fields
        @dataclasses.dataclass
        class V2:
            a: int
            b: int = 42

        wire._BY_ID[1000] = V2
        wire._FIELDS[1000] = dataclasses.fields(V2)
        got = wire.loads(old)
        assert (got.a, got.b) == (7, 42)
    finally:
        del wire._BY_ID[1000], wire._FIELDS[1000]
        del wire._BY_TYPE[V1]


def test_unregistered_type_rejected():
    class NotRegistered:
        pass

    with pytest.raises(wire.WireError):
        wire.dumps(NotRegistered())


def test_bad_magic_and_version():
    good = wire.dumps(1)
    with pytest.raises(wire.WireError):
        wire.loads(b"\x00" + good[1:])
    with pytest.raises(wire.WireError):
        wire.loads(bytes([wire.MAGIC, 99]) + good[2:])
    with pytest.raises(wire.WireError):
        wire.loads(good + b"x")  # trailing bytes


def test_decoder_fuzz_never_crashes():
    """Random and mutated frames: decode either succeeds (mutation hit a
    benign spot) or raises WireError — nothing else escapes."""
    rng = random.Random(1234)
    seeds = [
        wire.dumps(I.CommitTransactionRequest(
            read_snapshot=9, read_conflict_ranges=[(b"a", b"b")],
            mutations=[Mutation(MutationType.SET_VALUE, b"k", b"v" * 50)])),
        wire.dumps({b"k": [1, (2, 3)], "s": {1.5, True}}),
        wire.dumps([None, -12345, b"\xff" * 30]),
    ]
    for _ in range(3000):
        base = bytearray(rng.choice(seeds))
        for _ in range(rng.randint(1, 6)):
            base[rng.randrange(len(base))] = rng.randrange(256)
        try:
            wire.loads(bytes(base))
        except wire.WireError:
            pass
    for _ in range(2000):
        blob = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(0, 60)))
        try:
            wire.loads(blob)
        except wire.WireError:
            pass


def test_hostile_frames_raise_wireerror_only():
    # deep nesting: WireError, not RecursionError
    deep = bytes([wire.MAGIC, wire.WIRE_VERSION]) + b"l\x01" * 3000 + b"N"
    with pytest.raises(wire.WireError):
        wire.loads(deep)
    # unhashable set element: WireError, not TypeError
    with pytest.raises(wire.WireError):
        wire.loads(bytes([wire.MAGIC, wire.WIRE_VERSION]) + b"S\x01l\x00")
    # unhashable dict key
    with pytest.raises(wire.WireError):
        wire.loads(bytes([wire.MAGIC, wire.WIRE_VERSION]) + b"m\x01l\x00N")


def test_rpc_dataclasses_registered():
    """Every payload the real transport carries must be registered —
    coordination and ratekeeper RPCs ride NetTransport too."""
    from foundationdb_tpu.server import coordination as coord
    from foundationdb_tpu.server import ratekeeper as rk

    rt(coord.GenReadRequest(key="g", gen=1))
    rt(coord.GenWriteRequest(key="g", value={"m": "a:1"}, gen=2))
    rt(coord.CandidacyRequest(address="a:1", priority=1))
    rt(coord.LeaderReply(leader=None, priority=0))
    rt(rk.RateInfoReply(tps=100.0))
    rt(rk.QueueStatsReply(queue_bytes=10, lag_versions=5))


def test_container_bound():
    # a frame claiming a 16M-element list must be rejected, not allocated
    evil = bytes([wire.MAGIC, wire.WIRE_VERSION, ord("l")])
    out = bytearray(evil)
    wire._w_varint(out, 1 << 25)
    with pytest.raises(wire.WireError):
        wire.loads(bytes(out))
