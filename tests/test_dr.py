"""DR agent: continuous replication to a second live cluster + switchover.

Reference: fdbclient/DatabaseBackupAgent.actor.cpp (dr_agent,
atomicSwitchover) and the BackupToDBCorrectness workload: a destination
cluster converges to the source under live writes, and a switchover yields
byte-identical data through the fence version.
"""

import pytest

from foundationdb_tpu.backup.dr import DR_PRIMARY, DRAgent
from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.core.sim import SimNetwork
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom
from foundationdb_tpu.utils.types import MutationType


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def two_clusters(seed=5):
    loop = EventLoop()
    rng = DeterministicRandom(seed)
    net = SimNetwork(loop, rng.fork())
    a = SimCluster(seed=seed, n_proxies=2, n_storage=2, loop=loop, net=net,
                   name_prefix="a-")
    b = SimCluster(seed=seed + 1, n_storage=2, loop=loop, net=net,
                   name_prefix="b-")
    return loop, a, b


async def read_user_rows(db):
    async def rd(tr):
        return await tr.get_range(b"", b"\xff", limit=100_000)
    return await db.transact(rd, max_retries=500)


def test_dr_replicates_and_switches_over():
    loop, a, b = two_clusters()
    src = a.database("clientA:0")
    dst = b.database("clientB:0")
    agent = DRAgent(src, dst, chunk_rows=50)

    async def t():
        # pre-existing data (must arrive via the initial snapshot)
        async def seed(tr):
            for i in range(120):
                tr.set(b"pre/%04d" % i, b"v%04d" % i)
        await src.transact(seed)

        await agent.start()
        v0 = await agent.initial_snapshot()
        assert v0 > 0
        tail = loop.spawn(agent.run(), name="drTail")

        # live writes while the tail runs: sets, clears, atomic adds —
        # including an overwrite of snapshot data
        async def live(tr):
            for i in range(40):
                tr.set(b"live/%04d" % i, b"L%04d" % i)
            tr.clear_range(b"pre/0000", b"pre/0010")
            tr.atomic_op(MutationType.ADD_VALUE, b"ctr",
                         (7).to_bytes(8, "little"))
        for _ in range(5):
            await src.transact(live, max_retries=200)
            await loop.delay(0.3)

        # convergence: destination watermark reaches the source's state
        for _ in range(100):
            rows_src = await read_user_rows(src)
            rows_dst = await read_user_rows(dst)
            if rows_src == rows_dst:
                break
            await loop.delay(0.5)
        assert await read_user_rows(dst) == await read_user_rows(src), \
            "destination never converged"

        # a few more writes, then switchover (writers quiesced)
        async def more(tr):
            tr.set(b"final", b"state")
            tr.atomic_op(MutationType.ADD_VALUE, b"ctr",
                         (1).to_bytes(8, "little"))
        await src.transact(more, max_retries=200)
        end_version = await agent.switchover()
        assert end_version > v0
        await tail  # run() exits once deactivated + drained

        rows_src = await read_user_rows(src)
        rows_dst = await read_user_rows(dst)
        assert rows_src == rows_dst, \
            (f"switchover not byte-identical: {len(rows_src)} vs "
             f"{len(rows_dst)} rows")
        assert (b"final", b"state") in rows_dst
        ctr = dict(rows_dst)[b"ctr"]
        assert int.from_bytes(ctr, "little") == 36  # 5*7 + 1

        async def primary(tr):
            return await tr.get(DR_PRIMARY)
        assert await dst.transact(primary) == b"primary"

    loop.run_future(loop.spawn(t()), max_time=600_000.0)


def test_dr_drain_is_idempotent_across_duplicate_application():
    """The applied-version watermark makes replayed batches no-ops: applying
    the same tee rows twice (a crashed agent's replay) must not double-apply
    atomic ops."""
    loop, a, b = two_clusters(seed=9)
    src = a.database("clientA:0")
    dst = b.database("clientB:0")
    agent = DRAgent(src, dst)

    async def t():
        await agent.start()
        await agent.initial_snapshot()

        async def add(tr):
            tr.atomic_op(MutationType.ADD_VALUE, b"n",
                         (5).to_bytes(8, "little"))
        await src.transact(add, max_retries=200)

        # capture the tee rows, apply once via drain, then REPLAY the same
        # rows by hand (simulating a crash after apply but before clear)
        from foundationdb_tpu.backup.agent import BLOG_END, BLOG_PREFIX
        rows = []

        async def snap(tr):
            nonlocal rows
            rows = await tr.get_range(BLOG_PREFIX, BLOG_END)
        await src.transact(snap)
        assert rows
        await agent.drain_once()

        async def replant(tr):
            for k, v in rows:
                tr.set(k, v)
        await src.transact(replant, max_retries=200)
        await agent.drain_once()

        async def rd(tr):
            return await tr.get(b"n")
        n = await dst.transact(rd, max_retries=200)
        assert int.from_bytes(n, "little") == 5, \
            f"duplicate application doubled the atomic op: {n}"

    loop.run_future(loop.spawn(t()), max_time=600_000.0)


def test_dr_switchover_under_fault_cocktail():
    """BackupToDBCorrectness with faults: the DR stream keeps replicating
    while links clog and disk-backed roles (tlogs, storages) are killed and
    rebooted on BOTH clusters; after healing, switchover must still be
    byte-identical."""
    from foundationdb_tpu.core.sim import KillType
    from foundationdb_tpu.utils.errors import FDBError

    loop, a, b = two_clusters(seed=7)
    src = a.database("clientA:0")
    dst = b.database("clientB:0")
    agent = DRAgent(src, dst, chunk_rows=30)
    rng = DeterministicRandom(7001)

    async def t():
        async def seed(tr):
            for i in range(80):
                tr.set(b"pre/%04d" % i, b"v%04d" % i)
        await src.transact(seed)

        await agent.start()
        v0 = await agent.initial_snapshot()
        assert v0 > 0
        tail = loop.spawn(agent.run(), name="drTail")

        # kill storage procs only: they recover from their WAL and re-pull
        # the log to catch up. A SimCluster has no master/CC recovery, so a
        # killed TLOG would wedge commits forever on its missed-version gap
        # (the proxy's version chain never fills) — tlog kills under real
        # recovery are RecoverableCluster territory (tests/test_backup.py
        # cocktail, tests/test_sim_tiers.py).
        victims = ([p.address for p in a.storage_procs]
                   + [p.address for p in b.storage_procs])
        everyone = (victims
                    + [p.address for p in a.tlog_procs]
                    + [p.address for p in b.tlog_procs]
                    + [p.address for p in a.proxy_procs]
                    + [p.address for p in b.proxy_procs])

        async def live_with_faults():
            for n in range(12):
                async def w(tr, n=n):
                    tr.set(b"live/%04d" % n, b"L%04d" % n)
                    tr.clear_range(b"pre/%04d" % (n * 3),
                                   b"pre/%04d" % (n * 3 + 1))
                    tr.atomic_op(MutationType.ADD_VALUE, b"ctr",
                                 (3).to_bytes(8, "little"))
                try:
                    await src.transact(w, max_retries=1000)
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                x = everyone[rng.randint(0, len(everyone) - 1)]
                y = everyone[rng.randint(0, len(everyone) - 1)]
                if x != y:
                    a.net.clog_pair(x, y, 1.5 * rng.random())
                if rng.coinflip(0.4):
                    v = victims[rng.randint(0, len(victims) - 1)]
                    a.net.kill(v, KillType.RebootProcess)
                await loop.delay(0.5 + 0.5 * rng.random())
        await live_with_faults()

        a.net.heal()
        a.net.reboot_dead()
        await loop.delay(2.0)

        # convergence under a healed network, then the fence
        for _ in range(200):
            if await read_user_rows(dst) == await read_user_rows(src):
                break
            await loop.delay(0.5)
        end_version = await agent.switchover()
        assert end_version > v0
        await tail

        rows_src = await read_user_rows(src)
        rows_dst = await read_user_rows(dst)
        assert rows_src == rows_dst, \
            (f"switchover not byte-identical under faults: "
             f"{len(rows_src)} vs {len(rows_dst)} rows")
        # the counter's exact value depends on commit_unknown_result
        # retries under faults; the DR invariant is src/dst equality,
        # plus the atomic op must have applied at least the 12 rounds
        ctr = int.from_bytes(dict(rows_dst)[b"ctr"], "little")
        assert ctr >= 36 and ctr % 3 == 0, ctr

        async def primary(tr):
            return await tr.get(DR_PRIMARY)
        assert await dst.transact(primary) == b"primary"

    loop.run_future(loop.spawn(t()), max_time=600_000.0)
