"""DR agent: continuous replication to a second live cluster + switchover.

Reference: fdbclient/DatabaseBackupAgent.actor.cpp (dr_agent,
atomicSwitchover) and the BackupToDBCorrectness workload: a destination
cluster converges to the source under live writes, and a switchover yields
byte-identical data through the fence version.
"""

import pytest

from foundationdb_tpu.backup.dr import DR_PRIMARY, DRAgent
from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.core.sim import SimNetwork
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom
from foundationdb_tpu.utils.types import MutationType


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def two_clusters(seed=5):
    loop = EventLoop()
    rng = DeterministicRandom(seed)
    net = SimNetwork(loop, rng.fork())
    a = SimCluster(seed=seed, n_proxies=2, n_storage=2, loop=loop, net=net,
                   name_prefix="a-")
    b = SimCluster(seed=seed + 1, n_storage=2, loop=loop, net=net,
                   name_prefix="b-")
    return loop, a, b


async def read_user_rows(db):
    async def rd(tr):
        return await tr.get_range(b"", b"\xff", limit=100_000)
    return await db.transact(rd, max_retries=500)


def test_dr_replicates_and_switches_over():
    loop, a, b = two_clusters()
    src = a.database("clientA:0")
    dst = b.database("clientB:0")
    agent = DRAgent(src, dst, chunk_rows=50)

    async def t():
        # pre-existing data (must arrive via the initial snapshot)
        async def seed(tr):
            for i in range(120):
                tr.set(b"pre/%04d" % i, b"v%04d" % i)
        await src.transact(seed)

        await agent.start()
        v0 = await agent.initial_snapshot()
        assert v0 > 0
        tail = loop.spawn(agent.run(), name="drTail")

        # live writes while the tail runs: sets, clears, atomic adds —
        # including an overwrite of snapshot data
        async def live(tr):
            for i in range(40):
                tr.set(b"live/%04d" % i, b"L%04d" % i)
            tr.clear_range(b"pre/0000", b"pre/0010")
            tr.atomic_op(MutationType.ADD_VALUE, b"ctr",
                         (7).to_bytes(8, "little"))
        for _ in range(5):
            await src.transact(live, max_retries=200)
            await loop.delay(0.3)

        # convergence: destination watermark reaches the source's state
        for _ in range(100):
            rows_src = await read_user_rows(src)
            rows_dst = await read_user_rows(dst)
            if rows_src == rows_dst:
                break
            await loop.delay(0.5)
        assert await read_user_rows(dst) == await read_user_rows(src), \
            "destination never converged"

        # a few more writes, then switchover (writers quiesced)
        async def more(tr):
            tr.set(b"final", b"state")
            tr.atomic_op(MutationType.ADD_VALUE, b"ctr",
                         (1).to_bytes(8, "little"))
        await src.transact(more, max_retries=200)
        end_version = await agent.switchover()
        assert end_version > v0
        await tail  # run() exits once deactivated + drained

        rows_src = await read_user_rows(src)
        rows_dst = await read_user_rows(dst)
        assert rows_src == rows_dst, \
            (f"switchover not byte-identical: {len(rows_src)} vs "
             f"{len(rows_dst)} rows")
        assert (b"final", b"state") in rows_dst
        ctr = dict(rows_dst)[b"ctr"]
        assert int.from_bytes(ctr, "little") == 36  # 5*7 + 1

        async def primary(tr):
            return await tr.get(DR_PRIMARY)
        assert await dst.transact(primary) == b"primary"

    loop.run_future(loop.spawn(t()), max_time=600_000.0)


def test_dr_drain_is_idempotent_across_duplicate_application():
    """The applied-version watermark makes replayed batches no-ops: applying
    the same tee rows twice (a crashed agent's replay) must not double-apply
    atomic ops."""
    loop, a, b = two_clusters(seed=9)
    src = a.database("clientA:0")
    dst = b.database("clientB:0")
    agent = DRAgent(src, dst)

    async def t():
        await agent.start()
        await agent.initial_snapshot()

        async def add(tr):
            tr.atomic_op(MutationType.ADD_VALUE, b"n",
                         (5).to_bytes(8, "little"))
        await src.transact(add, max_retries=200)

        # capture the tee rows, apply once via drain, then REPLAY the same
        # rows by hand (simulating a crash after apply but before clear)
        from foundationdb_tpu.backup.agent import BLOG_END, BLOG_PREFIX
        rows = []

        async def snap(tr):
            nonlocal rows
            rows = await tr.get_range(BLOG_PREFIX, BLOG_END)
        await src.transact(snap)
        assert rows
        await agent.drain_once()

        async def replant(tr):
            for k, v in rows:
                tr.set(k, v)
        await src.transact(replant, max_retries=200)
        await agent.drain_once()

        async def rd(tr):
            return await tr.get(b"n")
        n = await dst.transact(rd, max_retries=200)
        assert int.from_bytes(n, "little") == 5, \
            f"duplicate application doubled the atomic op: {n}"

    loop.run_future(loop.spawn(t()), max_time=600_000.0)
