"""Commit batcher behavior: flush triggers (count / bytes / interval), the
arrival-rate-adaptive interval, the bounded multi-batch pipeline window, the
empty-batch keepalive, deterministic batch numbering under sim, and the
client's AIMD commit admission control.

Reference: MasterProxyServer.actor.cpp commitBatcher (COMMIT_TRANSACTION_
BATCH_* knobs) and GrvProxyServer's transaction budget; the pipelined
version-batch window is the reference's overlapping commitBatch actors
ordered by NotifiedVersion waits.
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.core.future import Future
from foundationdb_tpu.server.cluster import RecoverableCluster, SimCluster
from foundationdb_tpu.utils import trace as T
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _knobs():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield
    KNOBS.reset()


def _pump(cluster, dt: float = 0.001):
    """Run the sim loop briefly so spawned background actors start (a
    constructed-but-never-run cluster leaves them as unawaited coroutines)."""
    async def idle():
        await cluster.loop.delay(dt)
    cluster.run_all([idle()], max_time=10.0)


def _commit_n(cluster, db, n, max_time=600.0, prefix=b"cb"):
    async def one(i):
        tr = db.create_transaction()
        tr.set(b"%s%04d" % (prefix, i), b"v" * 8)
        await tr.commit()
    cluster.run_all([one(i) for i in range(n)], max_time=max_time)


# ------------------------------------------------------------ flush triggers

def test_count_trigger_flushes_before_interval():
    """COUNT_MAX reached -> the batch dispatches immediately; with the
    interval knobs set far beyond the test horizon, only the count trigger
    can explain the commits completing."""
    KNOBS.set("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 4)
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 30.0)
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MAX", 30.0)
    c = SimCluster(seed=3, n_proxies=1)
    db = c.database()
    t0 = c.loop.now()
    _commit_n(c, db, 8, max_time=20.0)
    assert c.loop.now() - t0 < 20.0
    assert c.proxies[0]._c_batches.value >= 2


def test_bytes_trigger_flushes_before_interval():
    """BATCH_BYTES_MIN reached -> immediate dispatch, same horizon logic."""
    KNOBS.set("COMMIT_TRANSACTION_BATCH_BYTES_MIN", 64)
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 30.0)
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MAX", 30.0)
    c = SimCluster(seed=4, n_proxies=1)
    db = c.database()

    async def big():
        tr = db.create_transaction()
        tr.set(b"bigkey", b"x" * 200)  # alone exceeds BYTES_MIN
        await tr.commit()
    t0 = c.loop.now()
    c.run_all([big()], max_time=20.0)
    assert c.loop.now() - t0 < 20.0


def test_interval_trigger_flushes_lone_commit():
    """A single small commit (neither count nor bytes trigger) still
    dispatches after the batch interval."""
    KNOBS.set("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 10_000)
    KNOBS.set("COMMIT_TRANSACTION_BATCH_BYTES_MIN", 1 << 30)
    c = SimCluster(seed=5, n_proxies=1)
    db = c.database()
    _commit_n(c, db, 1, max_time=60.0)
    assert c.proxies[0].stats["committed"] == 1


# ------------------------------------------------------- adaptive interval

def test_target_interval_slides_with_arrival_rate():
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 0.001)
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MAX", 0.010)
    KNOBS.set("COMMIT_BATCH_RATE_SATURATION", 1000.0)
    c = SimCluster(seed=6, n_proxies=1)
    _pump(c)  # start the roles' background actors
    px = c.proxies[0]
    px._arrival_rate = 0.0
    assert px._target_interval() == pytest.approx(0.001)
    px._arrival_rate = 500.0  # half of saturation -> mid interval
    assert px._target_interval() == pytest.approx(0.0055)
    px._arrival_rate = 5000.0  # beyond saturation clamps at MAX
    assert px._target_interval() == pytest.approx(0.010)
    # degenerate config (MAX <= MIN) pins to MIN instead of inverting
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MAX", 0.0005)
    assert px._target_interval() == pytest.approx(0.001)


def test_target_interval_scales_with_proxy_pool():
    """The saturation rate is cluster-wide: a proxy in a pool of n sees
    1/n of the commit rate but batches as if it saw all of it, so
    fan-out does not re-fragment batches through the shared
    master/resolvers/tlogs. The cap stays at INTERVAL_MAX — stretching
    the flush wait past it just converts closed-loop client throughput
    into idle queueing."""
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 0.001)
    KNOBS.set("COMMIT_TRANSACTION_BATCH_INTERVAL_MAX", 0.010)
    KNOBS.set("COMMIT_BATCH_RATE_SATURATION", 1000.0)
    c = SimCluster(seed=14, n_proxies=2)
    _pump(c)
    px = c.proxies[0]
    px._arrival_rate = 0.0  # light load: latency wins regardless of pool
    assert px._target_interval() == pytest.approx(0.001)
    # each of 2 proxies at 250/s == half of cluster saturation: the pool
    # sits at the same mid-curve point a lone proxy at 500/s would
    px._arrival_rate = 250.0
    assert px._target_interval() == pytest.approx(0.0055)
    # cluster saturation (2 x 500/s) clamps at MAX, never n x MAX
    px._arrival_rate = 500.0
    assert px._target_interval() == pytest.approx(0.010)


def test_arrival_rate_ewma_rises_under_load():
    KNOBS.set("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 4)
    c = SimCluster(seed=7, n_proxies=1)
    db = c.database()
    _commit_n(c, db, 40)
    assert c.proxies[0]._arrival_rate > 0.0


# ------------------------------------------------------------ pipeline window

def test_inflight_batches_bounded_by_pipeline_depth():
    """With many batches forced (COUNT_MAX=1) the number of concurrently
    in-flight version batches never exceeds COMMIT_PIPELINE_DEPTH, and the
    pipeline actually overlaps batches (depth observed > 1)."""
    KNOBS.set("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 1)
    KNOBS.set("COMMIT_PIPELINE_DEPTH", 2)
    c = SimCluster(seed=8, n_proxies=1)
    px = c.proxies[0]
    seen: list[int] = []
    orig = px._flush

    def spy():
        orig()
        seen.append(px._inflight_batches)
    px._flush = spy
    db = c.database()
    _commit_n(c, db, 30)
    assert seen and max(seen) <= 2
    assert max(seen) > 1, "pipeline never overlapped two batches"
    assert px._inflight_batches == 0  # every batch released its slot


def test_depth_one_serializes_batches():
    KNOBS.set("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 1)
    KNOBS.set("COMMIT_PIPELINE_DEPTH", 1)
    c = SimCluster(seed=9, n_proxies=1)
    px = c.proxies[0]
    seen: list[int] = []
    orig = px._flush

    def spy():
        orig()
        seen.append(px._inflight_batches)
    px._flush = spy
    db = c.database()
    _commit_n(c, db, 12)
    assert seen and max(seen) == 1
    assert px._inflight_batches == 0


# -------------------------------------------------------- empty-batch keepalive

def test_empty_batch_keepalive_advances_committed_version():
    """An idle proxy still pushes empty batches every IDLE_INTERVAL so
    storage servers' version horizon (and GRV recency) keeps moving."""
    c = SimCluster(seed=10, n_proxies=1)
    px = c.proxies[0]
    # statically-built sim proxies don't start the keepalive (it exists for
    # recruited clusters whose storage horizon must keep moving); start it
    # here to test the loop itself
    px._empty_task = px.process.spawn(px._empty_batch_loop(), "emptyBatch")

    async def idle():
        await c.loop.delay(5 * KNOBS.COMMIT_BATCH_IDLE_INTERVAL)
    c.run_all([idle()], max_time=60.0)
    assert px.committed_version.get() > 0
    assert px.stats["commits_in"] == 0


# ------------------------------------------------- deterministic numbering

def _batch_ids(seed: int) -> list[str]:
    got: list[dict] = []
    KNOBS.set("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 2)
    KNOBS.set("COMMIT_PIPELINE_DEPTH", 4)
    T.g_trace_batch._events.clear()  # drop other tests' buffered records
    try:
        T.set_sink(got.append)
        c = SimCluster(seed=seed, n_proxies=2)
        db = c.database()
        _commit_n(c, db, 24)
        T.g_trace_batch.dump()
    finally:
        T.set_sink(None)
        T.g_trace_batch._events.clear()
    return [e["ID"] for e in got
            if e.get("Span") == "Proxy.BatchAssembly"
            and e.get("Phase") == "Begin"]


def test_batch_numbering_deterministic_with_pipelining():
    """Same seed => identical batch-id sequence even with a >1 pipeline
    window (batch numbers are assigned at flush, not at completion)."""
    a = _batch_ids(seed=21)
    b = _batch_ids(seed=21)
    assert a and a == b
    # distinct per-proxy monotonic numbering, no reuse
    assert len(a) == len(set(a))


# ------------------------------------------------------ client admission

def test_admission_bounds_in_flight_commits():
    KNOBS.set("CLIENT_COMMIT_INITIAL_IN_FLIGHT", 3)
    KNOBS.set("CLIENT_COMMIT_MAX_IN_FLIGHT", 3)
    c = SimCluster(seed=12, n_proxies=1)
    db = c.database()
    peak = [0]
    done = [0]

    async def monitor():
        while done[0] < 20:
            peak[0] = max(peak[0], db._commits_in_flight)
            await c.loop.delay(0.0002)

    async def one(i):
        tr = db.create_transaction()
        tr.set(b"adm%04d" % i, b"v")
        await tr.commit()
        done[0] += 1
    c.run_all([monitor()] + [one(i) for i in range(20)], max_time=600.0)
    assert peak[0] <= 3
    assert db._commits_in_flight == 0 and not db._commit_queue


def test_admission_feedback_aimd():
    c = SimCluster(seed=13, n_proxies=1)
    _pump(c)
    db = c.database()
    db._commit_budget = 8.0

    ok = Future()
    ok._set(object())
    # healthy acks: additive increase, bounded by MAX
    db._admission_feedback(ok, 0.010)
    assert db._commit_budget > 8.0
    db._commit_budget = float(KNOBS.CLIENT_COMMIT_MAX_IN_FLIGHT)
    db._admission_feedback(ok, 0.010)
    assert db._commit_budget == float(KNOBS.CLIENT_COMMIT_MAX_IN_FLIGHT)

    # throttle signal: multiplicative cut, floored at 1
    db._commit_budget = 10.0
    throttled = Future()
    throttled._set_error(FDBError("transaction_throttled", "0.1 00 ff"))
    db._admission_feedback(throttled, 0.001)
    assert db._commit_budget == pytest.approx(
        10.0 * KNOBS.CLIENT_ADMISSION_DECREASE)
    # a second cut inside the same window is suppressed (one cut per event)
    db._admission_feedback(throttled, 0.001)
    assert db._commit_budget == pytest.approx(
        10.0 * KNOBS.CLIENT_ADMISSION_DECREASE)

    # latency inflation vs the learned floor also cuts
    db2 = c.database("client:aimd2")
    db2._commit_budget = 10.0
    db2._admission_feedback(ok, 0.010)  # learn the floor
    assert db2._commit_lat_floor == pytest.approx(0.010)
    db2._admission_feedback(
        ok, 0.010 * (KNOBS.CLIENT_ADMISSION_LATENCY_RATIO + 1))
    assert db2._commit_budget < 10.0

    # conflicts say nothing about queueing: budget untouched
    db3 = c.database("client:aimd3")
    db3._commit_budget = 10.0
    conflict = Future()
    conflict._set_error(FDBError("not_committed"))
    db3._admission_feedback(conflict, 0.010)
    assert db3._commit_budget == 10.0


# ------------------------------------------------- grv/commit proxy split

def test_grv_split_recruited_and_routed():
    """The CC recruits dedicated GRV proxies on their own workers, the
    DBInfo publishes them, and a refreshed client routes read versions to
    the GRV pool while commits stay on the commit pool."""
    c = RecoverableCluster(seed=11, n_workers=5, n_proxies=2,
                           n_grv_proxies=1, n_resolvers=1, n_tlogs=2,
                           n_storage=2)
    db = c.database()

    async def work():
        await db.refresh(max_wait=300.0)
        assert db.grv_proxies, "grv pool empty after refresh"
        assert not set(db.grv_proxies) & set(db.proxies), \
            "grv proxy co-listed in the commit pool"

        async def fn(tr):
            tr.set(b"split", b"1")
        await db.transact(fn, max_retries=50)
        tr = db.create_transaction()
        assert await tr.get(b"split") == b"1"
        status = await db.get_status()
        roles = [e["role"] for e in status["cluster"]["roles"]]
        assert "grv_proxy" in roles
    c.run(c.loop.spawn(work(), "work"), max_time=60_000.0)
